"""Program-tier audit: the bucketed production programs traced to
jaxprs and checked structurally.

Four invariants, each cheap because everything here is TRACE-ONLY
(``jax.make_jaxpr`` over ``jax.eval_shape``-derived abstract params —
no compile, no execute, no device memory):

- **no-S²** — every attention formulation that claims streaming/tiled
  semantics must never materialize the (B, H, S, S) score tensor or the
  broadcast rel-pos bias: largest intermediate anywhere in the traced
  attention jaxpr stays below S*S elements (PR 1's fused/xlaflash
  assert, generalized to all impls; ``densefolded`` is dense BY DESIGN
  and exempt — its max is recorded informationally).
- **no-f64** — no equation output anywhere in a production program may
  be float64/complex128: on TPU a silent f64 upcast runs in emulation,
  on CPU it silently doubles bandwidth, and either way the oracle pins
  never blessed those numerics.
- **quant-widen** — inside the quantized path (TMR_QUANT=int8), no
  ``convert_element_type`` may widen beyond 32-bit floats: the int8
  dequant arithmetic is pinned at f32 accumulation, and a stray f64
  dequant would both break the quant_ok bound and destroy the win.
- **transfer-guard** — ``device_put`` equations per program are pinned
  to the expected count (trace-time constant placement; a NEW one means
  someone put a mid-program host hop into a hot path) and host
  callbacks (``pure_callback``/``io_callback``/``debug_callback``) must
  be ZERO — the rtt_floor regression mode. The device_put pin is
  per-platform (CPU constant staging differs from TPU), resolved
  baseline.transfer_guard[platform][program] first, then the in-code
  defaults.

``audit_production_programs`` is the entry point scripts/analyze.py,
gate_probe.py, and bench.py share; ``audit_jaxpr`` is the reusable
single-jaxpr predicate the fixture tests drive directly.
"""

from __future__ import annotations

import math
import os
import warnings
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: attention impls contractually bound to < S*S intermediates.
#: ``densefolded`` is excluded: it is the dense small-grid formulation,
#: S² materialization is its design point (the gate elects it only
#: where that fits VMEM).
NO_S2_ATTN_IMPLS = ("blockwise", "blockfolded", "flash", "xlaflash",
                    "pallas", "fused")

#: attention impls that trace without TPU hardware present; audited set
DENSE_BY_DESIGN = ("densefolded",)

#: expected trace-time ``device_put`` count per production program at
#: the PRODUCTION backbone (sam_vit_b) — measured on the committed tree
#: (they come from numpy constants the trace stages: the ViT rel-pos
#: tables and norm stats; a resnet program stages none). Override per
#: platform via analysis_baseline.json ``transfer_guard`` when a backend
#: stages constants differently, or per call via ``transfer_pins`` when
#: auditing a non-default backbone/geometry.
DEFAULT_TRANSFER_PINS: Dict[str, int] = {
    "match_heads": 24,
    "match_heads_dp": 24,  # the shard_map dp serve variant: same ViT
    # constants staged inside the shard_map body — a drift from the
    # unsharded pin means the sharded trace grew a host hop of its own
    "backbone": 24,
    "heads_only": 0,
    "nms_topk": 0,
}

#: the three trace-time gate knobs whose cross product defines the
#: audited gate states (the PR 6 surface)
GATE_KNOBS = ("TMR_DECODER_IMPL", "TMR_QUANT", "TMR_DECODE_TAIL")

#: the full 2x2x2 sweep test coverage pins
ALL_GATE_STATES: Tuple[Dict[str, str], ...] = tuple(
    {"TMR_DECODER_IMPL": di, "TMR_QUANT": q, "TMR_DECODE_TAIL": dt}
    for di in ("xla", "fused")
    for q in ("off", "int8")
    for dt in ("host", "device")
)


# --------------------------------------------------------------------------
# jaxpr predicates
# --------------------------------------------------------------------------


def _sub_jaxprs(eqn):
    # params may hold a jaxpr directly (scan/pjit 'jaxpr'), or a
    # tuple/list of them (cond/switch 'branches') — missing the latter
    # would blind every invariant inside conditional branches
    for val in eqn.params.values():
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns"):
                yield inner


def iter_eqns(jaxpr):
    """Every equation in a jaxpr, sub-jaxprs (scan/pjit/pallas bodies)
    included, depth-first."""
    for eqn in jaxpr.eqns:
        yield eqn
        for inner in _sub_jaxprs(eqn):
            yield from iter_eqns(inner)


def jaxpr_stats(jaxpr) -> dict:
    """The structural facts every audit rule reads, in one walk:
    largest intermediate (elements), f64/complex128 equation count,
    widening convert_element_type count (target float wider than 32
    bits), device_put count, host-callback count."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    stats = {
        "max_intermediate_elems": 0,
        "f64_eqns": 0,
        "widening_converts": 0,
        "device_put": 0,
        "callbacks": 0,
    }
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "device_put":
            stats["device_put"] += 1
        elif "callback" in name:
            stats["callbacks"] += 1
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is None:
                continue
            shape = getattr(aval, "shape", None)
            if shape is not None:
                stats["max_intermediate_elems"] = max(
                    stats["max_intermediate_elems"],
                    int(math.prod(shape)),
                )
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and str(dtype) in ("float64",
                                                    "complex128"):
                stats["f64_eqns"] += 1
                if name == "convert_element_type":
                    stats["widening_converts"] += 1
    return stats


def int8_reach_stats(jaxpr) -> dict:
    """Prove stored int8 weights actually FEED the matmuls (the
    TMR_QUANT_STORAGE audit): walk the jaxpr tainting every value
    transitively derived from an int8 program input (or int8 constant),
    and count the ``dot_general`` equations consuming a tainted or
    directly-int8 operand. The storage contract is that the program's
    int8 invars reach the dots through in-program widening only — a
    tree upconverted to f32 BEFORE the program boundary would show
    ``int8_invars == 0`` here even though the numerics still pass the
    equality pin (that is exactly the silent failure this rule exists
    to catch: the bytes would never have moved).

    Taint propagation is deliberately over-approximate (any equation
    with a tainted input taints all its outputs); sub-jaxprs map taint
    positionally where the invar lists line up (pjit) and fall back to
    whole-body tainting elsewhere (scan/cond) — over-taint can only
    produce a false PASS for a program with int8 inputs feeding nothing,
    which ``int8_invars`` plus the dot counts make visible."""
    from jax import core as _core

    Literal = _core.Literal
    top = getattr(jaxpr, "jaxpr", jaxpr)
    stats = {"int8_invars": 0, "dot_eqns": 0, "int8_fed_dots": 0,
             "int8_operand_dots": 0, "conv_eqns": 0,
             "int8_fed_convs": 0}

    def is_int8(v):
        dtype = getattr(getattr(v, "aval", None), "dtype", None)
        return dtype is not None and str(dtype) == "int8"

    def walk(jx, seed) -> bool:
        """Returns True when any outvar of ``jx`` ends tainted."""
        tainted = set(seed)
        for eqn in jx.eqns:
            name = eqn.primitive.name
            real_ins = [v for v in eqn.invars
                        if not isinstance(v, Literal)]
            any_t = any(v in tainted for v in real_ins)
            direct = any(is_int8(v) for v in real_ins)
            if name == "dot_general":
                stats["dot_eqns"] += 1
                if direct:
                    stats["int8_operand_dots"] += 1
                if any_t or direct:
                    stats["int8_fed_dots"] += 1
            elif name == "conv_general_dilated":
                stats["conv_eqns"] += 1
                if any_t or direct:
                    stats["int8_fed_convs"] += 1
            inner_tainted = False
            for val in eqn.params.values():
                items = val if isinstance(val, (tuple, list)) else (val,)
                for item in items:
                    inner = getattr(item, "jaxpr", item)
                    if not hasattr(inner, "eqns"):
                        continue
                    iseed = set(
                        v for v in getattr(inner, "constvars", ())
                        if is_int8(v)
                    )
                    if len(inner.invars) == len(eqn.invars):
                        for outer, iv in zip(eqn.invars, inner.invars):
                            if not isinstance(outer, Literal) and (
                                outer in tainted or is_int8(outer)
                            ):
                                iseed.add(iv)
                    elif any_t or direct:
                        iseed.update(inner.invars)
                    if walk(inner, iseed):
                        inner_tainted = True
            if any_t or direct or inner_tainted:
                tainted.update(
                    v for v in eqn.outvars if not isinstance(v, Literal)
                )
        return any(v in tainted for v in jx.outvars
                   if not isinstance(v, Literal))

    seed = set()
    for v in top.invars:
        if is_int8(v):
            stats["int8_invars"] += 1
            seed.add(v)
    seed.update(v for v in getattr(top, "constvars", ()) if is_int8(v))
    walk(top, seed)
    return stats


def audit_jaxpr(
    jaxpr,
    name: str,
    s2_bound: Optional[int] = None,
    quant: bool = False,
    transfer_pin: Optional[int] = None,
) -> dict:
    """Audit one traced program. Returns a record with the measured
    stats, a ``problems`` list (empty == clean), and ``ok``.

    ``s2_bound``: when set, max intermediate must stay strictly below it
    (the no-S² rule — pass S*S for an attention trace, omit for full
    programs whose legitimate tensors dwarf the reduced-geometry S²).
    ``quant``: apply the quant-widen rule (widening converts must be 0).
    ``transfer_pin``: expected device_put count (None = unpinned);
    callbacks must always be 0."""
    stats = jaxpr_stats(jaxpr)
    problems: List[str] = []
    if s2_bound is not None and stats["max_intermediate_elems"] >= s2_bound:
        problems.append(
            f"{name}: materializes a {stats['max_intermediate_elems']}-"
            f"element intermediate (bound S^2 = {s2_bound})"
        )
    if stats["f64_eqns"]:
        problems.append(
            f"{name}: {stats['f64_eqns']} float64/complex128 equation(s) "
            "in a production program"
        )
    if quant and stats["widening_converts"]:
        problems.append(
            f"{name}: {stats['widening_converts']} widening "
            "convert_element_type(s) beyond f32 inside the quantized path"
        )
    if stats["callbacks"]:
        problems.append(
            f"{name}: {stats['callbacks']} host callback(s) mid-program — "
            "the rtt_floor regression mode; hot paths must stay on device"
        )
    if transfer_pin is not None and stats["device_put"] != transfer_pin:
        problems.append(
            f"{name}: {stats['device_put']} device_put equation(s), "
            f"pinned {transfer_pin} for this platform — a new one means a "
            "host hop snuck into the program (update the per-platform pin "
            "in analysis_baseline.json transfer_guard only for an "
            "understood constant-staging change)"
        )
    return {"name": name, **stats, "s2_bound": s2_bound,
            "transfer_pin": transfer_pin, "quant": quant,
            "problems": problems, "ok": not problems}


# --------------------------------------------------------------------------
# attention-impl audit (PR 1's no-S² assert, generalized)
# --------------------------------------------------------------------------


def _attention_impl_fns() -> Dict[str, callable]:
    from tmr_tpu.models.vit import (
        blockfolded_decomposed_attention,
        blockwise_decomposed_attention,
        densefolded_decomposed_attention,
    )
    from tmr_tpu.ops.flash_attn import (
        flash_decomposed_attention,
        xla_flash_decomposed_attention,
    )
    from tmr_tpu.ops.pallas_attn import (
        pallas_decomposed_attention,
        pallas_fused_attention,
    )

    return {
        "blockwise": blockwise_decomposed_attention,
        "blockfolded": blockfolded_decomposed_attention,
        "densefolded": densefolded_decomposed_attention,
        "flash": flash_decomposed_attention,
        "xlaflash": xla_flash_decomposed_attention,
        "pallas": pallas_decomposed_attention,
        "fused": pallas_fused_attention,
    }


def audit_attention_impls(
    grids: Sequence[Tuple[int, int]] = ((64, 64),),
    head_dim: int = 64,
    impls: Optional[Iterable[str]] = None,
) -> dict:
    """Trace every attention formulation at the given grids and apply
    the no-S² bound to the contractually-streaming ones. Trace-only —
    the production 64x64 grid costs ~0.1 s per impl on CPU."""
    import jax
    import jax.numpy as jnp

    fns = _attention_impl_fns()
    wanted = list(impls) if impls is not None else sorted(fns)
    out: Dict[str, dict] = {}
    ok = True
    for gh, gw in grids:
        S = gh * gw
        q = jax.ShapeDtypeStruct((1, 2, S, head_dim), jnp.bfloat16)
        rh = jax.ShapeDtypeStruct((gh, gh, head_dim), jnp.float32)
        rw = jax.ShapeDtypeStruct((gw, gw, head_dim), jnp.float32)
        for name in wanted:
            fn = fns[name]
            label = f"attn:{name}@{gh}x{gw}"
            bound = S * S if name in NO_S2_ATTN_IMPLS else None
            try:
                jaxpr = jax.make_jaxpr(
                    lambda a, b, c, d, e, _f=fn: _f(
                        a, b, c, d, e, (gh, gw), head_dim**-0.5
                    )
                )(q, q, q, rh, rw)
            except Exception as e:  # an impl that cannot trace here is
                out[label] = {"name": label, "ok": True,  # not audited
                              "skipped": f"{type(e).__name__}: {e}"}
                continue
            rec = audit_jaxpr(jaxpr, label, s2_bound=bound)
            out[label] = rec
            ok = ok and rec["ok"]
    return {"grids": [list(g) for g in grids], "head_dim": head_dim,
            "impls": out, "dense_by_design": list(DENSE_BY_DESIGN),
            "ok": ok}


# --------------------------------------------------------------------------
# production-program audit
# --------------------------------------------------------------------------


def _platform() -> str:
    import jax

    return jax.default_backend()


def current_gate_state() -> Dict[str, str]:
    return {
        "TMR_DECODER_IMPL": os.environ.get("TMR_DECODER_IMPL", "auto"),
        "TMR_QUANT": os.environ.get("TMR_QUANT", "off"),
        "TMR_DECODE_TAIL": os.environ.get("TMR_DECODE_TAIL", "host"),
        "TMR_QUANT_STORAGE": os.environ.get("TMR_QUANT_STORAGE", "off"),
    }


def audit_storage_program(
    image_size: int = 32,
    emb_dim: int = 16,
    max_detections: int = 32,
    backbone: str = "resnet50_layer1",
) -> dict:
    """The stored-int8 program audited for REAL int8 reach: under
    TMR_QUANT_STORAGE=int8 (caller's env) a tiny-geometry Predictor is
    given real params, the stored tree is materialized through the full
    admission path (quant.stored_params_for), and the traced fused
    program is checked for (a) int8 invars — the program boundary
    actually receives int8 arrays, no silent upconvert — and (b) those
    invars feeding the decoder/head ``dot_general`` equations
    (:func:`int8_reach_stats`), plus the standard no-f64 / quant-widen /
    no-callback rules. Real (tiny) init instead of eval_shape because
    the stored tree's scales are concrete trace constants; ~1 s on CPU.
    """
    import jax
    import jax.numpy as jnp

    from tmr_tpu.inference import Predictor

    cfg = _audit_cfg(image_size, emb_dim, max_detections, backbone)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=image_size)
    problems: List[str] = []
    st = pred._storage_state()
    if st is None:
        from tmr_tpu.diagnostics import gate_refusals

        problems.append(
            "storage: TMR_QUANT_STORAGE=int8 was not admitted for the "
            "audit predictor (see recorded quant_storage_ok causes: "
            f"{[r['message'] for r in gate_refusals()[-3:]]})"
        )
        return {"name": "match_heads_stored", "ok": False,
                "problems": problems}
    cap = int(cfg.template_buckets[0])
    img = jax.ShapeDtypeStruct((1, image_size, image_size, 3),
                               jnp.float32)
    ex = jax.ShapeDtypeStruct((1, 1, 4), jnp.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        jaxpr = jax.make_jaxpr(pred._get_fn(cap))(st.tree, None, img, ex)
    rec = audit_jaxpr(jaxpr, "match_heads_stored", quant=True,
                      transfer_pin=None)
    reach = int8_reach_stats(jaxpr)
    rec.update(reach)
    k = int(cfg.decoder_kernel_size)
    # one conv = k^2 tap dots, + the block-diagonal head dot; every one
    # of them must be fed from an int8 invar
    min_dots = k * k + 1
    if reach["int8_invars"] < len(st.paths):
        problems.append(
            f"storage: program receives {reach['int8_invars']} int8 "
            f"invars but the stored tree holds {len(st.paths)} int8 "
            "leaves — something upconverted the tree at the boundary"
        )
    if reach["int8_fed_dots"] < min_dots:
        problems.append(
            f"storage: only {reach['int8_fed_dots']} dot_general "
            f"equation(s) fed from int8 inputs (expected >= {min_dots}: "
            f"{k}x{k} taps + the block-diagonal head)"
        )
    problems.extend(rec["problems"])
    rec["problems"] = problems
    rec["ok"] = not problems
    rec["stored_leaves"] = len(st.paths)
    rec["digest"] = st.digest[:16]
    return rec


def _audit_cfg(image_size: int, emb_dim: Optional[int],
               max_detections: int, backbone: str):
    from tmr_tpu.config import preset

    kw = dict(backbone=backbone, image_size=image_size,
              compute_dtype="float32", batch_size=1,
              max_detections=max_detections)
    if emb_dim is not None:
        kw["emb_dim"] = emb_dim
    return preset("TMR_FSCD147", **kw)


def _transfer_pin(baseline, platform: str, program: str,
                  overrides: Optional[Dict[str, int]] = None
                  ) -> Optional[int]:
    if overrides is not None:
        return overrides.get(program)
    if baseline is not None:
        pin = baseline.transfer_pin(platform, program)
        if pin is not None:
            return int(pin.get("device_put", 0)) if isinstance(
                pin, dict
            ) else int(pin)
    return DEFAULT_TRANSFER_PINS.get(program)


def _trace_programs(pred, params, image_size: int, batch: int,
                    programs: Sequence[str]) -> Dict[str, object]:
    """Trace the requested production programs under the CURRENT env
    knobs; returns {name: ClosedJaxpr}. Every trace is abstract —
    ShapeDtypeStruct inputs, eval_shape params."""
    import jax
    import jax.numpy as jnp

    img1 = jax.ShapeDtypeStruct((1, image_size, image_size, 3),
                                jnp.float32)
    ex1 = jax.ShapeDtypeStruct((1, 1, 4), jnp.float32)
    imgB = jax.ShapeDtypeStruct((batch, image_size, image_size, 3),
                                jnp.float32)
    exB = jax.ShapeDtypeStruct((batch, 1, 4), jnp.float32)
    cap = int(pred.cfg.template_buckets[0])
    out: Dict[str, object] = {}
    with warnings.catch_warnings():
        # a pinned-but-refused formulation warns FormulationFallback —
        # the audit then audits the fallback, which is what will run
        warnings.simplefilter("ignore")
        if "match_heads" in programs:
            out["match_heads"] = jax.make_jaxpr(pred._get_fn(cap))(
                params, None, img1, ex1
            )
        if "backbone" in programs or "heads_only" in programs:
            bb = pred._get_backbone_fn()
            if "backbone" in programs:
                out["backbone"] = jax.make_jaxpr(bb)(params, imgB)
            if "heads_only" in programs:
                feat = jax.eval_shape(bb, params, imgB)
                out["heads_only"] = jax.make_jaxpr(
                    pred._get_heads_fn(cap, image_size)
                )(params, None, feat, exB)
        if "nms_topk" in programs:
            from tmr_tpu.ops.pallas_nms import nms_topk

            boxes = jax.ShapeDtypeStruct((batch, 64, 4), jnp.float32)
            scores = jax.ShapeDtypeStruct((batch, 64), jnp.float32)
            valid = jax.ShapeDtypeStruct((batch, 64), jnp.bool_)
            out["nms_topk"] = jax.make_jaxpr(
                lambda b, s, v: nms_topk(b, s, 0.5, valid=v, k=32)
            )(boxes, scores, valid)
        if "match_heads_dp" in programs:
            # the mesh-sharded serving variant (shard_map over dp, the
            # bitwise-exact fan-out path): trace-only like everything
            # here — the shard_map in_specs path needs no real params.
            # Needs >= 2 local devices for a dp-2 mesh; a single-device
            # runtime records a skip instead of failing the audit (the
            # forced-8-device test conftest is where the pin is load-
            # bearing).
            if len(jax.devices()) >= 2:
                from tmr_tpu.serve.meshplan import MeshPlan

                plan = MeshPlan("dp2", devices=jax.devices())
                dp_batch = max(2, batch + (batch % 2))
                img_dp = jax.ShapeDtypeStruct(
                    (dp_batch, image_size, image_size, 3), jnp.float32
                )
                ex_dp = jax.ShapeDtypeStruct((dp_batch, 1, 4),
                                             jnp.float32)
                out["match_heads_dp"] = jax.make_jaxpr(
                    pred._get_sharded_fn(cap, plan.dp_target)
                )(params, None, img_dp, ex_dp)
    return out


def audit_production_programs(
    baseline=None,
    image_size: int = 64,
    emb_dim: Optional[int] = None,
    max_detections: int = 64,
    batch: int = 2,
    backbone: str = "sam_vit_b",
    transfer_pins: Optional[Dict[str, int]] = None,
    gate_states: Optional[Sequence[Dict[str, str]]] = None,
    programs: Sequence[str] = ("match_heads", "match_heads_dp",
                               "backbone", "heads_only", "nms_topk"),
    attention_grids: Sequence[Tuple[int, int]] = ((64, 64),),
    include_attention: bool = True,
    record_refusals: bool = False,
) -> dict:
    """The full program-tier audit record (the ``program_audit`` section
    of analysis_report/v1).

    ``gate_states``: list of env-knob dicts to sweep (each audits the
    knob-dependent programs; the FIRST state audits everything
    requested). None = audit once under the ambient env — what bench.py
    wants after autotune exported its winners. ``record_refusals``: on a
    failing program, record a structured ``gate_probe/v1`` cause via
    diagnostics.gate_refused — the same contract the kernel gates keep,
    so an autotune-elected path that fails the audit travels with WHY.
    """
    platform = _platform()
    cfg = _audit_cfg(image_size, emb_dim, max_detections, backbone)

    import jax
    import jax.numpy as jnp

    from tmr_tpu.inference import Predictor

    pred = Predictor(cfg)
    params = jax.eval_shape(
        lambda k: pred.model.init(
            k,
            jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            jnp.zeros((1, 1, 4), jnp.float32),
        ),
        jax.random.key(0),
    )["params"]

    states = list(gate_states) if gate_states is not None else [None]
    state_records: List[dict] = []
    problems: List[str] = []
    saved = {k: os.environ.get(k) for k in GATE_KNOBS}
    try:
        for i, state in enumerate(states):
            if state is not None:
                for k in GATE_KNOBS:
                    if state.get(k) is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = state[k]
                pred._compiled.clear()  # knobs are read at trace time
            wanted = (
                programs if i == 0
                else [p for p in programs
                      if p in ("match_heads", "heads_only")]
            )
            quant = os.environ.get("TMR_QUANT", "off") == "int8"
            jaxprs = _trace_programs(pred, params, image_size, batch,
                                     wanted)
            recs = []
            for name, jaxpr in jaxprs.items():
                rec = audit_jaxpr(
                    jaxpr, name, quant=quant,
                    transfer_pin=_transfer_pin(baseline, platform, name,
                                               transfer_pins),
                )
                recs.append(rec)
                problems.extend(rec["problems"])
                if record_refusals and not rec["ok"]:
                    from tmr_tpu.diagnostics import gate_refused

                    gate_refused(
                        "program_audit", "; ".join(rec["problems"]),
                        "forward-mismatch",
                        config={"program": name, "platform": platform,
                                **current_gate_state()},
                    )
            state_records.append({
                "gate_state": current_gate_state(),
                "programs": recs,
                "ok": all(r["ok"] for r in recs),
            })
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if gate_states is not None:
            pred._compiled.clear()

    attention = None
    if include_attention:
        attention = audit_attention_impls(grids=attention_grids)
        problems.extend(
            p for rec in attention["impls"].values()
            for p in rec.get("problems", ())
        )
        if record_refusals and not attention["ok"]:
            from tmr_tpu.diagnostics import gate_refused

            gate_refused(
                "program_audit",
                "attention no-S^2 audit failed",
                "forward-mismatch",
                config={"program": "attention", "platform": platform},
            )

    # storage audit: when the ambient env elects TMR_QUANT_STORAGE=int8
    # (autotune export / explicit pin), prove the int8 leaves reach the
    # matmuls with real (tiny) params — the states sweep above traces
    # abstract eval_shape params, which cannot exercise the stored tree
    storage = None
    if os.environ.get("TMR_QUANT_STORAGE", "off") == "int8":
        try:
            storage = audit_storage_program()
        except Exception as e:
            storage = {"name": "match_heads_stored", "ok": False,
                       "problems": [
                           f"storage audit raised {type(e).__name__}: {e}"
                       ]}
        problems.extend(storage["problems"])
        if record_refusals and not storage["ok"]:
            from tmr_tpu.diagnostics import gate_refused

            gate_refused(
                "program_audit", "; ".join(storage["problems"]),
                "forward-mismatch",
                config={"program": "match_heads_stored",
                        "platform": platform, **current_gate_state()},
            )

    return {
        "platform": platform,
        "geometry": {"image_size": image_size,
                     "emb_dim": emb_dim or cfg.emb_dim,
                     "batch": batch},
        "states": state_records,
        "attention": attention,
        "storage": storage,
        "problems": problems,
        "ok": not problems,
    }
