"""The AST-tier analysis passes.

Six rules over the source tree (registered in core.RULES):

- ``jit-hygiene``      — no Python side effects lexically inside a
  jit-compiled function body: ``time.*`` / ``np.random`` / stdlib
  ``random`` calls, env reads, ``print``, and mutable-global writes all
  execute ONCE at trace time and then silently never again (or worse,
  leak host values into a cached program). Applies to functions
  decorated ``jax.jit`` / ``pjit`` / ``shard_map`` (including through
  ``functools.partial`` and local ``jit = ...`` aliases — the
  Predictor._compiled programs) and to functions wrapped post-hoc via
  ``jax.jit(fn)``.
- ``lock-discipline``  — in the threaded modules (tmr_tpu/serve/*,
  utils/faults.py, obs/metrics.py): an instance attribute accessed from
  more than one thread entry point (public method, ``threading.Thread``
  target, or a bound method whose reference escapes) must be WRITTEN
  only under a held ``self._lock``/``self._cond``-style context, or be
  a documented atomic in the baseline's ``lock_atomics`` whitelist.
  Module-level mutable globals in those files get the same treatment.
- ``knob-parity``      — every TMR_* env knob consumed under tmr_tpu/
  must be documented in ``config.ENV_KNOBS``; every registry entry must
  be consumed somewhere on the repo surface (tmr_tpu/ + bench.py +
  scripts/); descriptions must be non-empty. The knob registry IS how a
  knob read "goes through config.py" — an unregistered read is the bug.
- ``knob-import-time`` — no TMR_* knob may be read at import time
  outside config.py: a module-level read (direct, or through a helper
  called at module level) freezes the knob before any consumer can set
  it, which is how silently-dead knobs are born.
- ``report-parity``    — every ``*_report/v1`` schema constant in
  diagnostics.py ships a ``validate_*`` function, and every script
  referencing a ``*_REPORT_SCHEMA`` constant calls its validator
  (the self-check-before-print discipline).
- ``stdout-hygiene``   — stdout under tmr_tpu/ is machine-readable
  protocol output only; a bare ``print()`` in library code corrupts
  whatever pipeline parses it.

Pure ``ast``/``re`` — no jax import, cheap enough for tier-1 every run.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tmr_tpu.analysis.core import AnalysisContext, Finding, rule

# --------------------------------------------------------------------------
# shared helpers
# --------------------------------------------------------------------------


def _const_str(node) -> Optional[str]:
    return (node.value if isinstance(node, ast.Constant)
            and isinstance(node.value, str) else None)


def _is_environ(node) -> bool:
    """Does this expression denote ``os.environ`` / ``environ`` /
    ``getenv``? (the test_small_utils detector, now framework-owned)."""
    return ("environ" in ast.dump(node)) or (
        isinstance(node, ast.Attribute) and node.attr == "getenv"
    ) or (isinstance(node, ast.Name) and node.id == "getenv")


def _env_read_key(node) -> Tuple[bool, Optional[str]]:
    """(is an env read, literal key or None) for one AST node."""
    if isinstance(node, ast.Subscript) and _is_environ(node.value):
        return True, _const_str(node.slice)
    if isinstance(node, ast.Call) and (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in ("get", "pop", "setdefault", "getenv")
        and _is_environ(node.func)
    ):
        return True, _const_str(node.args[0]) if node.args else None
    return False, None


def env_knob_reads(tree: ast.AST, prefix: str = "TMR_") -> Dict[str, int]:
    """Literal ``prefix``-keyed env reads in a tree: {knob: first line}."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        is_read, key = _env_read_key(node)
        if is_read and key and key.startswith(prefix):
            out.setdefault(key, node.lineno)
    return out


def _dotted(node) -> List[str]:
    """Attribute/Name chain as a name list, outermost last:
    ``np.random.default_rng`` -> ['np', 'random', 'default_rng']."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


# --------------------------------------------------------------------------
# jit-hygiene
# --------------------------------------------------------------------------

#: names a jit-returning decorator resolves to locally (inference.py's
#: ``jit = functools.partial(jax.jit, ...)`` alias pattern)
_JIT_NAMES = ("jit", "pjit", "shard_map")


def _is_jitish(node) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    if isinstance(node, ast.Call):
        f = node.func
        if (isinstance(f, ast.Name) and f.id == "partial") or (
            isinstance(f, ast.Attribute) and f.attr == "partial"
        ):
            return any(_is_jitish(a) for a in node.args)
        return _is_jitish(f)
    return False


def _jit_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    """Every function the file compiles under jit: decorator-marked, or
    wrapped post-hoc by a ``jax.jit(fn)``-shaped call naming a local
    def."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jitish(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                wrapped.add(node.args[0].id)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_jitish(d) for d in node.decorator_list):
            out.append(node)
        elif node.name in wrapped:
            out.append(node)
    return out


def _module_mutable_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable containers (literal or a
    well-known constructor) — the things a traced function must never
    write."""
    ctors = {"dict", "list", "set", "OrderedDict", "defaultdict",
             "Counter", "deque"}
    out: Set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            targets, value = [stmt.target], stmt.value
        else:
            continue
        if value is None:
            continue
        chain = _dotted(value.func) if isinstance(value, ast.Call) else []
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
            bool(chain) and chain[-1] in ctors
        )
        if mutable:
            out.update(t.id for t in targets)
    return out


#: container-mutating method names (instruments like Counter.inc are
#: internally locked by contract and deliberately NOT listed)
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "update", "setdefault", "add",
    "discard", "move_to_end",
))


@rule("jit-hygiene")
def jit_hygiene(ctx: AnalysisContext) -> Iterable[Finding]:
    for rel in ctx.lib_files():
        tree = ctx.tree(rel)
        mut_globals = _module_mutable_globals(tree)
        for fn in _jit_functions(tree):
            where = f"jit function {fn.name!r}"
            declared_global: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared_global.update(node.names)
            for node in ast.walk(fn):
                line = getattr(node, "lineno", fn.lineno)
                is_read, key = _env_read_key(node)
                if is_read:
                    yield Finding(
                        "jit-hygiene", rel, line,
                        f"{where} reads the environment"
                        f"{f' ({key})' if key else ''} — captured once at "
                        "trace time, dead thereafter",
                    )
                    continue
                if not isinstance(node, (ast.Call, ast.Assign,
                                         ast.AugAssign)):
                    continue
                if isinstance(node, ast.Call):
                    chain = _dotted(node.func)
                    if chain[:1] == ["time"] and len(chain) > 1:
                        yield Finding(
                            "jit-hygiene", rel, line,
                            f"{where} calls time.{chain[1]} — a host "
                            "clock read inside a traced program is a "
                            "trace-time constant",
                        )
                    elif "random" in chain[:-1] and chain[0] in (
                        "np", "numpy", "random"
                    ):
                        yield Finding(
                            "jit-hygiene", rel, line,
                            f"{where} calls {'.'.join(chain)} — host "
                            "randomness inside a traced program freezes "
                            "at trace time (use jax.random)",
                        )
                    elif chain == ["print"]:
                        yield Finding(
                            "jit-hygiene", rel, line,
                            f"{where} calls print — executes once at "
                            "trace time, never per step",
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in mut_globals
                    ):
                        yield Finding(
                            "jit-hygiene", rel, line,
                            f"{where} mutates module global "
                            f"{node.func.value.id!r} — a side effect "
                            "captured under jit runs once per trace",
                        )
                else:  # Assign / AugAssign
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        name = None
                        if isinstance(t, ast.Name) and (
                            t.id in declared_global
                        ):
                            name = t.id
                        elif isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name
                        ) and t.value.id in mut_globals:
                            name = t.value.id
                        if name:
                            yield Finding(
                                "jit-hygiene", rel, line,
                                f"{where} writes global {name!r} — a "
                                "side effect captured under jit runs "
                                "once per trace",
                            )


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------

#: the threaded modules the pass audits (the serve pipeline's three free-
#: running thread pools, the fault-injection log the heartbeat threads
#: write, and the metrics/cache layers they all share)
LOCK_FILES = (
    "tmr_tpu/serve/batcher.py",
    "tmr_tpu/serve/staging.py",
    "tmr_tpu/serve/engine.py",
    "tmr_tpu/serve/caches.py",
    "tmr_tpu/serve/admission.py",
    "tmr_tpu/serve/degrade.py",
    "tmr_tpu/serve/feature_tier.py",
    "tmr_tpu/serve/fleet.py",
    "tmr_tpu/serve/gallery.py",
    "tmr_tpu/serve/gallery_index.py",
    "tmr_tpu/serve/streams.py",
    "tmr_tpu/autotune_live.py",
    "tmr_tpu/parallel/elastic.py",
    "tmr_tpu/parallel/leases.py",
    "tmr_tpu/utils/faults.py",
    "tmr_tpu/obs/metrics.py",
    "tmr_tpu/obs/fleetobs.py",
)


def _is_lock_ctx(expr) -> bool:
    """Is a ``with`` context expression a lock/condition hold? Matches
    ``self._lock`` / ``self._cond`` style attributes and module-level
    ``_LOCK``-style names (substring match on lock/cond, any case)."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        return _is_lock_ctx(expr.func)
    if name is None:
        return False
    low = name.lower()
    return "lock" in low or "cond" in low or "mutex" in low


def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id == "self":
        return node.attr
    return None


class _Access:
    __slots__ = ("attr", "line", "write", "locked")

    def __init__(self, attr: str, line: int, write: bool, locked: bool):
        self.attr, self.line = attr, line
        self.write, self.locked = write, locked


def _method_accesses(fn, target_names) -> Tuple[List[_Access], List[Tuple[
        str, bool]], bool]:
    """Walk one function: (attribute/global accesses with lock state,
    intra-class call sites [(callee, locked)], has_any_lock)."""
    accesses: List[_Access] = []
    calls: List[Tuple[str, bool]] = []

    def visit(node, locked: bool):
        if isinstance(node, ast.With):
            inner = locked or any(
                _is_lock_ctx(item.context_expr) for item in node.items
            )
            for item in node.items:
                visit(item.context_expr, locked)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            # nested defs (thread bodies, callbacks) keep the enclosing
            # lock state only if entered inline — conservatively treat
            # their bodies as NOT lock-held
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                name = _self_attr(t)
                if name is None and isinstance(t, ast.Subscript):
                    name = _self_attr(t.value)
                    if name is None and isinstance(t.value, ast.Name) \
                            and t.value.id in target_names:
                        name = t.value.id
                if name is None and isinstance(t, ast.Name) \
                        and t.id in target_names:
                    name = t.id
                if name is not None:
                    accesses.append(_Access(name, node.lineno, True, locked))
        if isinstance(node, ast.Delete):
            for t in node.targets:
                name = None
                if isinstance(t, ast.Subscript):
                    name = _self_attr(t.value)
                    if name is None and isinstance(t.value, ast.Name) \
                            and t.value.id in target_names:
                        name = t.value.id
                if name is not None:
                    accesses.append(_Access(name, node.lineno, True, locked))
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                owner = _self_attr(f.value)
                if owner is None and isinstance(f.value, ast.Name) \
                        and f.value.id in target_names:
                    owner = f.value.id
                if owner is not None and f.attr in _MUTATORS:
                    accesses.append(
                        _Access(owner, node.lineno, True, locked)
                    )
                method = _self_attr(f)
                if method is not None:
                    calls.append((method, locked))
        name = _self_attr(node)
        if name is not None and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            accesses.append(_Access(name, node.lineno, False, locked))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in fn.body:
        visit(stmt, False)
    return accesses, calls, any(a.locked for a in accesses)


def _class_findings(rel: str, cls: ast.ClassDef, ctx: AnalysisContext
                    ) -> Iterable[Finding]:
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    if not methods:
        return
    # thread roots: threading.Thread(target=self.X) + escaped bound
    # methods (self.X referenced outside a call position) + every public
    # method (each its own root: two public methods may race from two
    # caller threads)
    roots: Dict[str, Set[str]] = {name: set() for name in methods}
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if chain[-1:] == ["Thread"] or chain[-1:] == ["Timer"]:
                for kw in node.keywords:
                    if kw.arg == "target":
                        t = _self_attr(kw.value)
                        if t in methods:
                            roots[t].add(t)
        name = _self_attr(node)
        if name in methods and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            # bare bound-method reference (not the func of a Call — that
            # case never reaches here because _dotted consumed it; a
            # conservative check: any Load of self.<method> counts)
            roots[name].add(name)
    # the Load check above also catches `self.m()` call funcs; narrow:
    # a method used strictly as call target everywhere is not "escaped".
    called_only: Set[str] = set()
    for name in methods:
        loads, callfuncs = 0, 0
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and _self_attr(node.func) == name:
                callfuncs += 1
            elif _self_attr(node) == name:
                loads += 1
        if loads == 0 or loads == callfuncs:
            called_only.add(name)
    for name in called_only:
        # keep explicit Thread targets as roots even when call-only
        is_thread_target = any(
            isinstance(node, ast.Call)
            and _dotted(node.func)[-1:] in (["Thread"], ["Timer"])
            and any(kw.arg == "target"
                    and _self_attr(kw.value) == name
                    for kw in node.keywords)
            for node in ast.walk(cls)
        )
        if not is_thread_target:
            roots[name].discard(name)
    for name in methods:
        if not name.startswith("_") or name in (
            "__call__", "__enter__", "__exit__", "__len__",
            "__contains__", "__iter__",
        ):
            roots[name].add(name)

    # per-method accesses + intra-class call graph
    acc: Dict[str, List[_Access]] = {}
    calls: Dict[str, List[Tuple[str, bool]]] = {}
    for name, fn in methods.items():
        acc[name], calls[name], _ = _method_accesses(fn, frozenset())

    # always-locked propagation: a private method whose every intra-class
    # call site is lock-held runs under the caller's lock
    always_locked: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name in methods:
            if name in always_locked:
                continue
            sites = [
                (caller, locked) for caller, cl in calls.items()
                for callee, locked in cl if callee == name
            ]
            if not sites or roots[name]:
                continue  # a root runs unlocked from outside by definition
            if all(locked or caller in always_locked
                   for caller, locked in sites):
                always_locked.add(name)
                changed = True

    # reachability: propagate root labels through the call graph
    reach: Dict[str, Set[str]] = {n: set(roots[n]) for n in methods}
    changed = True
    while changed:
        changed = False
        for caller in methods:
            if caller == "__init__":
                continue
            for callee, _locked in calls[caller]:
                if callee in reach and not reach[caller] <= reach[callee]:
                    reach[callee] |= reach[caller]
                    changed = True

    # attribute -> union of accessing methods' roots (construction-time
    # __init__ excluded)
    attr_roots: Dict[str, Set[str]] = {}
    for name, fn_acc in acc.items():
        if name == "__init__":
            continue
        for a in fn_acc:
            attr_roots.setdefault(a.attr, set()).update(reach[name])

    for name, fn_acc in acc.items():
        if name == "__init__":
            continue
        held = name in always_locked
        for a in fn_acc:
            if not a.write or a.locked or held:
                continue
            shared = attr_roots.get(a.attr, set())
            if len(shared) < 2 or not reach[name]:
                continue
            if ctx.baseline.is_atomic(rel, f"{cls.name}.{a.attr}"):
                continue
            yield Finding(
                "lock-discipline", rel, a.line,
                f"{cls.name}.{name} writes self.{a.attr} without holding "
                f"a lock, but the attribute is reachable from "
                f"{len(shared)} thread entry points "
                f"({', '.join(sorted(shared))}) — hold self._lock-style "
                "context or whitelist it as a documented atomic in "
                "analysis_baseline.json lock_atomics",
            )


def _module_global_findings(rel: str, tree: ast.Module,
                            ctx: AnalysisContext) -> Iterable[Finding]:
    """Module-level mutable globals in a threaded module must be mutated
    under a lock (or be baseline-whitelisted documented atomics)."""
    globals_ = _module_mutable_globals(tree)
    declared: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            declared.update(node.names)
    targets = frozenset(globals_ | declared)
    if not targets:
        return
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        accesses, _calls, _ = _method_accesses(node, targets)
        for a in accesses:
            if not a.write or a.locked:
                continue
            if a.attr not in targets:
                continue
            if ctx.baseline.is_atomic(rel, a.attr):
                continue
            yield Finding(
                "lock-discipline", rel, a.line,
                f"{node.name} mutates module global {a.attr!r} without a "
                "lock in a threaded module — hold a module lock or "
                "whitelist it as a documented atomic in "
                "analysis_baseline.json lock_atomics",
            )


@rule("lock-discipline")
def lock_discipline(ctx: AnalysisContext) -> Iterable[Finding]:
    import os

    for rel in LOCK_FILES:
        if not os.path.exists(os.path.join(ctx.root, rel)):
            continue
        tree = ctx.tree(rel)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                yield from _class_findings(rel, node, ctx)
        yield from _module_global_findings(rel, tree, ctx)
    # fixture/scan extension: any OTHER lib file that spawns threads
    # from inside a class is audited the same way (new thread pools must
    # not dodge the pass by living in a new file)
    for rel in ctx.lib_files():
        if rel in LOCK_FILES:
            continue
        src = ctx.source(rel)
        if "threading.Thread(" not in src:
            continue
        for node in ctx.tree(rel).body:
            if not isinstance(node, ast.ClassDef):
                continue
            seg = ast.get_source_segment(src, node) or ""
            if "threading.Thread(" in seg:
                yield from _class_findings(rel, node, ctx)


# --------------------------------------------------------------------------
# knob-parity / knob-import-time
# --------------------------------------------------------------------------


def _registry_entries(ctx: AnalysisContext) -> Dict[str, Tuple[int, str]]:
    """Parse config.py's ENV_KNOBS dict literal without importing:
    {knob: (line, description)}."""
    tree = ctx.tree("tmr_tpu/config.py")
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets, value = [node.target.id], node.value
        else:
            continue
        if "ENV_KNOBS" in targets and isinstance(value, ast.Dict):
            out = {}
            for k, v in zip(value.keys, value.values):
                key = _const_str(k)
                if key is not None:
                    out[key] = (k.lineno, _const_str(v) or "")
            return out
    raise AssertionError(
        "tmr_tpu/config.py: ENV_KNOBS dict literal not found — the knob "
        "registry moved or broke"
    )


@rule("knob-parity")
def knob_parity(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _registry_entries(ctx)
    consumed: Dict[str, Tuple[str, int]] = {}
    # consumption scan covers the DRIVER surface too (bench.py,
    # scripts/): a TMR_ knob introduced by a probe or bench driver is
    # part of the same env surface and must be registered — before
    # this, only tmr_tpu/ reads could trip the rule
    for rel in ctx.lib_files() + ctx.driver_files():
        for knob, line in env_knob_reads(ctx.tree(rel)).items():
            consumed.setdefault(knob, (rel, line))
    if not consumed:
        yield Finding(
            "knob-parity", "tmr_tpu/config.py", 1,
            "AST scan found no TMR_ knob reads under tmr_tpu/ — the "
            "scanner itself broke (there are dozens)",
        )
        return
    for knob, (rel, line) in sorted(consumed.items()):
        if knob not in registry:
            yield Finding(
                "knob-parity", rel, line,
                f"TMR_ knob {knob!r} is consumed but missing from "
                "config.ENV_KNOBS — add it with a one-line description",
            )
    # reverse: a documented knob nothing consumes is a stale entry
    # (driver knobs live in bench.py / scripts/, so scan repo-wide;
    # string-literal presence is enough for existence). config.py is
    # EXCLUDED from the surface — the registry dict itself contains
    # every knob name as a literal, which made the pre-framework
    # test_small_utils version of this check unable to ever fire.
    surface = "\n".join(
        ctx.source(rel)
        for rel in ctx.lib_files() + ctx.driver_files()
        if rel != "tmr_tpu/config.py"
    )
    for knob, (line, desc) in sorted(registry.items()):
        if f'"{knob}"' not in surface and f"'{knob}'" not in surface:
            yield Finding(
                "knob-parity", "tmr_tpu/config.py", line,
                f"config.ENV_KNOBS entry {knob!r} is consumed by no code "
                "on the repo surface — delete it or wire it up",
            )
        if not desc.strip():
            yield Finding(
                "knob-parity", "tmr_tpu/config.py", line,
                f"config.ENV_KNOBS[{knob!r}]: empty description",
            )


def _env_reading_functions(tree: ast.Module) -> Dict[str, Set[str]]:
    """Module functions that read the environment: {name: literal TMR_
    keys read directly inside (possibly empty)}."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        keys: Set[str] = set()
        reads = False
        for sub in ast.walk(node):
            is_read, key = _env_read_key(sub)
            if is_read:
                reads = True
                if key and key.startswith("TMR_"):
                    keys.add(key)
        if reads:
            out[node.name] = keys
    return out


@rule("knob-import-time")
def knob_import_time(ctx: AnalysisContext) -> Iterable[Finding]:
    for rel in ctx.lib_files():
        if rel == "tmr_tpu/config.py":
            continue  # the registry module is the one legal home
        tree = ctx.tree(rel)
        readers = _env_reading_functions(tree)

        def walk_skip_functions(node):
            """Import-time-reachable nodes only: function/lambda bodies
            execute later, class bodies execute at import."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield child
                yield from walk_skip_functions(child)

        for node in walk_skip_functions(tree):
            is_read, key = _env_read_key(node)
            if is_read and key and key.startswith("TMR_"):
                yield Finding(
                    "knob-import-time", rel, node.lineno,
                    f"TMR_ knob {key!r} read at import time — consumers "
                    "that set it after import silently see nothing; read "
                    "lazily at call/trace time",
                )
                continue
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name
            ) and node.func.id in readers:
                keys = {
                    a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and a.value.startswith("TMR_")
                } | readers[node.func.id]
                if keys:
                    yield Finding(
                        "knob-import-time", rel, node.lineno,
                        f"TMR_ knob(s) {sorted(keys)} read at import time "
                        f"via {node.func.id}() — consumers that set them "
                        "after import silently see nothing; resolve "
                        "lazily",
                    )


# --------------------------------------------------------------------------
# report-parity
# --------------------------------------------------------------------------

_SCHEMA_CONST_RE = re.compile(
    r'^([A-Z][A-Z_]*)_SCHEMA\s*=\s*"(\w+_report)/v\d+"', re.M
)
_SCHEMA_REF_RE = re.compile(r"\b([A-Z][A-Z_]*?)_REPORT_SCHEMA\b")


@rule("report-parity")
def report_parity(ctx: AnalysisContext) -> Iterable[Finding]:
    diag_rel = "tmr_tpu/diagnostics.py"
    diag_src = ctx.source(diag_rel)
    schemas = list(_SCHEMA_CONST_RE.finditer(diag_src))
    if not any(m.group(2).endswith("_report") for m in schemas):
        yield Finding(
            "report-parity", diag_rel, 1,
            "no *_report schema constants found in diagnostics.py — the "
            "scanner or the report protocol broke",
        )
        return
    for m in schemas:
        const, tag = m.group(1), m.group(2)
        validator = f"validate_{tag}"
        if f"def {validator}" not in diag_src:
            yield Finding(
                "report-parity", diag_rel,
                diag_src.count("\n", 0, m.start()) + 1,
                f"{const}_SCHEMA ({tag}/v*) has no diagnostics."
                f"{validator}() — a report format cannot drift in "
                "unvalidated",
            )
    for rel in ctx.driver_files():
        src = ctx.source(rel)
        for const in sorted(set(_SCHEMA_REF_RE.findall(src))):
            validator = f"validate_{const.lower()}_report"
            if validator not in src:
                line = src.count(
                    "\n", 0, src.find(f"{const}_REPORT_SCHEMA")
                ) + 1
                yield Finding(
                    "report-parity", rel, line,
                    f"references {const}_REPORT_SCHEMA but never calls "
                    f"{validator}() — emit-then-validate is the report "
                    "contract",
                )


# --------------------------------------------------------------------------
# stdout-hygiene
# --------------------------------------------------------------------------


@rule("stdout-hygiene")
def stdout_hygiene(ctx: AnalysisContext) -> Iterable[Finding]:
    for rel in ctx.lib_files():
        for node in ast.walk(ctx.tree(rel)):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and not any(kw.arg == "file" for kw in node.keywords)
            ):
                yield Finding(
                    "stdout-hygiene", rel, node.lineno,
                    "bare print() to stdout in library code — stdout is "
                    "machine-readable protocol output; use "
                    "profiling.log_* or print(..., file=sys.stderr)",
                )
