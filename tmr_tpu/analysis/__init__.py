"""Repo-wide static analysis & compiled-program audit.

Two tiers of correctness tooling, one framework:

- **AST tier** (:mod:`tmr_tpu.analysis.ast_passes`): file/AST walking
  passes over the source tree — jit-hygiene (no Python side effects
  captured under ``jax.jit``), lock-discipline (shared mutable state in
  the serve/fault thread pools must be written under a lock), knob
  discipline (ENV_KNOBS registry parity + no import-time knob reads),
  report-schema parity, and stdout hygiene. The one-off lints that grew
  in tests/test_small_utils.py across PRs 4-6 now live here as framework
  passes; the tests are thin wrappers.
- **Program tier** (:mod:`tmr_tpu.analysis.program_audit`): the bucketed
  production programs (backbone, fused match+heads, heads-only,
  nms_topk) traced to jaxprs and audited structurally — no S²
  materialization in any no-S² attention formulation, no f64 anywhere,
  no widening ``convert_element_type`` in the quantized path, and a
  transfer guard pinning the ``device_put``/host-callback count per
  program (per-platform: CPU staging differs from TPU).

Entry points: :func:`run_analysis` (everything, one
``analysis_report/v1`` document — what ``scripts/analyze.py`` emits),
:func:`tmr_tpu.analysis.core.run_ast_passes` (AST tier only; what the
tier-1 test wrappers call), and a committed suppression baseline
(``analysis_baseline.json``) so pre-existing, documented exceptions
don't drown new findings.
"""

from tmr_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Baseline,
    Finding,
    RULES,
    build_report,
    default_baseline_path,
    run_analysis,
    run_ast_passes,
)
