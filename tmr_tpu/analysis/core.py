"""Checker framework: file/AST walking, findings, the suppression
baseline, and the ``analysis_report/v1`` document builder.

Dependency-light on purpose — the AST tier imports nothing heavier than
``ast`` (no jax), so ``run_ast_passes`` is cheap enough to ride tier-1
on every run. The program tier (program_audit.py) is imported lazily by
:func:`run_analysis` only when requested.

Vocabulary:

- a **rule** is a named pass (``@rule("jit-hygiene")``) taking an
  :class:`AnalysisContext` and yielding :class:`Finding` objects;
- a **finding** is one defect claim: rule id + repo-relative file +
  line + message;
- the **baseline** (``analysis_baseline.json``, committed) suppresses
  documented exceptions: each suppression names a rule, a file, an
  optional message substring, and a REQUIRED human reason — a
  suppression without a why is a finding waiting to rot. It also
  carries the lock-discipline atomic whitelist and the per-platform
  transfer-guard pins the program tier reads.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Callable, Dict, Iterable, List, Optional

#: baseline document schema tag (the report schema lives in
#: tmr_tpu.diagnostics as ANALYSIS_REPORT_SCHEMA with its validator)
BASELINE_SCHEMA = "analysis_baseline/v1"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect claim from one rule at one source location."""

    rule: str
    file: str  # repo-relative, '/'-separated
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:  # the human-readable grep-able form
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


class Baseline:
    """The committed suppression set + pass-specific whitelists.

    ``allows(finding)`` is the one question the framework asks: does a
    suppression entry match this finding's rule AND file AND (when the
    entry carries ``match``) message substring? Line numbers are
    deliberately NOT part of the key — a baseline pinned to line numbers
    would churn on every unrelated edit above it.
    """

    def __init__(self, doc: Optional[dict] = None, path: str = ""):
        doc = doc or {}
        self.path = path
        self.suppressions: List[dict] = list(doc.get("suppressions", ()))
        #: lock-discipline documented atomics: [{"file", "attr", "reason"}]
        self.lock_atomics: List[dict] = list(doc.get("lock_atomics", ()))
        #: program-tier transfer pins: {platform: {program: {kind: n}}}
        self.transfer_guard: Dict[str, dict] = dict(
            doc.get("transfer_guard", {})
        )
        for i, s in enumerate(self.suppressions):
            for req in ("rule", "file", "reason"):
                if not s.get(req):
                    raise ValueError(
                        f"baseline suppression[{i}] missing {req!r}: {s}"
                    )
        for i, a in enumerate(self.lock_atomics):
            for req in ("file", "attr", "reason"):
                if not a.get(req):
                    raise ValueError(
                        f"baseline lock_atomics[{i}] missing {req!r}: {a}"
                    )

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls({}, path=path)
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != BASELINE_SCHEMA:
            raise ValueError(
                f"{path}: schema != {BASELINE_SCHEMA}: {doc.get('schema')!r}"
            )
        return cls(doc, path=path)

    def allows(self, finding: Finding) -> bool:
        for s in self.suppressions:
            if s["rule"] != finding.rule or s["file"] != finding.file:
                continue
            if s.get("match") and s["match"] not in finding.message:
                continue
            return True
        return False

    def is_atomic(self, file: str, attr: str) -> bool:
        return any(
            a["file"] == file and a["attr"] == attr
            for a in self.lock_atomics
        )

    def transfer_pin(self, platform: str, program: str) -> Optional[dict]:
        plat = self.transfer_guard.get(platform)
        if plat is None:
            return None
        return plat.get(program, plat.get("*"))

    def document(self) -> dict:
        return {
            "schema": BASELINE_SCHEMA,
            "suppressions": self.suppressions,
            "lock_atomics": self.lock_atomics,
            "transfer_guard": self.transfer_guard,
        }

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        with open(path, "w") as f:
            json.dump(self.document(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path


def default_repo_root() -> str:
    """The repo root this installed tree lives in (two levels above
    this file: tmr_tpu/analysis/core.py)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or default_repo_root(),
                        "analysis_baseline.json")


class AnalysisContext:
    """Shared state every pass reads: the file list and a parse cache
    (each file is read + AST-parsed at most once per run)."""

    #: directories (repo-relative) the AST tier walks for library code
    LIB_DIRS = ("tmr_tpu",)
    #: extra top-level surface files/dirs passes may scan (driver code)
    DRIVER = ("bench.py", "scripts")

    def __init__(self, root: Optional[str] = None,
                 baseline: Optional[Baseline] = None):
        self.root = os.path.abspath(root or default_repo_root())
        self.baseline = baseline or Baseline()
        self._src: Dict[str, str] = {}
        self._ast: Dict[str, ast.Module] = {}

    # ----------------------------------------------------------- file sets
    def _walk(self, *relpaths: str) -> List[str]:
        out: List[str] = []
        for rel in relpaths:
            path = os.path.join(self.root, rel)
            if os.path.isfile(path) and rel.endswith(".py"):
                out.append(rel)
                continue
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        out.append(
                            os.path.relpath(full, self.root).replace(
                                os.sep, "/"
                            )
                        )
        return sorted(out)

    def lib_files(self) -> List[str]:
        """Library sources (tmr_tpu/**/*.py), repo-relative."""
        return self._walk(*self.LIB_DIRS)

    def driver_files(self) -> List[str]:
        """Driver surface (bench.py + scripts/*.py), repo-relative."""
        return self._walk(*self.DRIVER)

    # --------------------------------------------------------- parse cache
    def source(self, rel: str) -> str:
        if rel not in self._src:
            with open(os.path.join(self.root, rel)) as f:
                self._src[rel] = f.read()
        return self._src[rel]

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._ast:
            self._ast[rel] = ast.parse(self.source(rel), filename=rel)
        return self._ast[rel]


#: rule id -> pass callable(ctx) -> iterable[Finding]
RULES: Dict[str, Callable[[AnalysisContext], Iterable[Finding]]] = {}


def rule(rule_id: str):
    """Register a pass under ``rule_id`` (its findings must carry it)."""

    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate analysis rule {rule_id!r}")
        RULES[rule_id] = fn
        fn.rule_id = rule_id
        return fn

    return deco


def run_ast_passes(
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    baseline: Optional[Baseline] = None,
) -> List[Finding]:
    """Run the AST-tier passes and return EVERY finding (baselined ones
    included — callers split with ``baseline.allows``). ``rules`` names a
    subset; default all registered."""
    import tmr_tpu.analysis.ast_passes  # noqa: F401 — registers RULES

    ctx = AnalysisContext(root=root, baseline=baseline)
    wanted = list(rules) if rules is not None else sorted(RULES)
    unknown = [r for r in wanted if r not in RULES]
    if unknown:
        raise KeyError(
            f"unknown analysis rules {unknown}; registered: {sorted(RULES)}"
        )
    findings: List[Finding] = []
    for rule_id in wanted:
        for f in RULES[rule_id](ctx):
            if f.rule != rule_id:
                raise AssertionError(
                    f"pass {rule_id!r} emitted a finding tagged {f.rule!r}"
                )
            findings.append(f)
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule,
                                           f.message))


def build_report(
    findings: List[Finding],
    baseline: Baseline,
    program_audit: Optional[dict] = None,
    root: str = "",
) -> dict:
    """Assemble the ``analysis_report/v1`` document (schema + validator
    in tmr_tpu.diagnostics): unbaselined findings in full, baselined ones
    as a count, per-rule tallies, the program-tier record when one ran,
    and the one verdict CI gates on (``checks.clean``)."""
    from tmr_tpu.diagnostics import ANALYSIS_REPORT_SCHEMA

    new = [f for f in findings if not baseline.allows(f)]
    suppressed = len(findings) - len(new)
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    program_ok = (program_audit or {}).get("ok", True)
    return {
        "schema": ANALYSIS_REPORT_SCHEMA,
        "root": root,
        "rules": sorted(RULES),
        "findings": [f.to_dict() for f in new],
        "baselined_count": suppressed,
        "counts_by_rule": by_rule,
        "program_audit": program_audit,
        "checks": {
            "ast_clean": not new,
            "program_ok": bool(program_ok),
            "clean": not new and bool(program_ok),
        },
    }


def run_analysis(
    root: Optional[str] = None,
    baseline_path: Optional[str] = None,
    with_program_audit: bool = True,
    program_kwargs: Optional[dict] = None,
) -> dict:
    """The full pass: AST tier + (optionally) the program-tier audit,
    returned as one validated ``analysis_report/v1`` document. This is
    what ``scripts/analyze.py`` emits and what CI gates on."""
    root = os.path.abspath(root or default_repo_root())
    baseline = Baseline.load(baseline_path or default_baseline_path(root))
    findings = run_ast_passes(root=root, baseline=baseline)
    program = None
    if with_program_audit:
        from tmr_tpu.analysis.program_audit import audit_production_programs

        program = audit_production_programs(
            baseline=baseline, **(program_kwargs or {})
        )
    doc = build_report(findings, baseline, program_audit=program, root=root)
    from tmr_tpu.diagnostics import validate_analysis_report

    problems = validate_analysis_report(doc)
    if problems:  # the emitter self-check discipline (serve_bench's rule)
        raise AssertionError(f"invalid analysis_report/v1: {problems}")
    return doc
