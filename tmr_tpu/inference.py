"""End-to-end inference: one jitted program per (image-size, template) bucket.

Covers the reference's eval/demo inference paths:
- trainer.py each_step test branch (:143-150): forward -> Get_pred_boxes ->
  [refine] -> NMS;
- each_step_multi_exemplars (:75-121): per-exemplar forward + decode, concat,
  one NMS over the union;
- demo.py Inference.infer (:102-132).

The whole chain — encoder, template match, heads, peak decode, NMS — is ONE
XLA program (the fused-inference north star of BASELINE.json). Dynamic shape
sources (input resolution 1024/1536, template size) become a small set of
host-selected static buckets, each compiled once and cached.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.models import build_model
from tmr_tpu.models.matching_net import select_capacity_bucket
from tmr_tpu.ops.postprocess import batched_nms, decode_detections


class Predictor:
    """Bucketed-jit inference wrapper around MatchingNet.

    With ``refiner`` set (and cfg.refine_box), the pipeline becomes
    forward -> decode -> SAM box refinement -> NMS, the reference test-step
    order (trainer.py:143-150) — still one fused XLA program. The refiner
    consumes the model's own pre-upsample backbone features instead of the
    reference's second ViT-H pass (trainer.py:146-147).
    """

    def __init__(self, cfg, params=None, model=None, refiner=None,
                 refiner_params=None):
        self.cfg = cfg
        self.model = model if model is not None else build_model(cfg)
        self.params = params
        self.refiner = refiner
        self.refiner_params = refiner_params
        self._compiled: Dict[Tuple[int, bool], callable] = {}  # (capacity, refine)
        self._nms_fn = None

    def init_params(self, seed: int = 0, image_size: Optional[int] = None):
        s = image_size or self.cfg.image_size
        image = jnp.zeros((1, s, s, 3), jnp.float32)
        exemplars = jnp.array([[[0.4, 0.4, 0.6, 0.6]]], jnp.float32)
        # jit the init: eager init dispatches thousands of tiny ops, which
        # is pathologically slow over a remote-device tunnel
        self.params = jax.jit(self.model.init)(
            jax.random.key(seed), image, exemplars
        )["params"]
        return self.params

    def feature_hw(self, image_size: int) -> int:
        bb = self.model.backbone
        stride = getattr(bb, "feature_stride", None) or getattr(
            bb, "patch_size", 16
        )
        base = image_size // stride
        return base * 2 if self.cfg.feature_upsample else base

    def _get_fn(self, capacity: int):
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        key = (capacity, refine)  # refine is baked into the compiled program
        if key in self._compiled:
            return self._compiled[key]
        model = self.model.clone(template_capacity=capacity)
        cfg = self.cfg
        refiner = self.refiner

        @jax.jit
        def run(params, refiner_params, image, exemplars):
            out = model.apply({"params": params}, image, exemplars)
            dets = decode_detections(
                out["objectness"],
                out["regressions"],
                exemplars[:, 0, :],
                cls_threshold=cfg.NMS_cls_threshold,
                max_detections=cfg.max_detections,
                box_reg=cfg.box_reg,
                scale_imgsize=cfg.regression_scaling_imgsize,
                scale_wh_only=cfg.regression_scaling_WH_only,
            )
            if refine:
                dets = refiner.refine(
                    refiner_params,
                    out["backbone_feature"],
                    dets,
                    (image.shape[1], image.shape[2]),
                )
            return batched_nms(dets, cfg.NMS_iou_threshold)

        self._compiled[key] = run
        return run

    def pick_capacity(self, exemplars: np.ndarray, image_size: int) -> int:
        """Host-side template bucket for a batch: the largest per-exemplar need."""
        hw = self.feature_hw(image_size)
        need = 1
        for ex in np.asarray(exemplars).reshape(-1, 4):
            need = max(
                need,
                select_capacity_bucket(ex, hw, hw, self.cfg.template_buckets),
            )
        return need

    def __call__(self, image, exemplars) -> dict:
        """image (B, S, S, 3) float32 normalized; exemplars (B, K, 4).
        Returns dict boxes/scores/refs/valid as fixed-shape device arrays."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        cap = self.pick_capacity(exemplars, int(image.shape[1]))
        fn = self._get_fn(cap)
        return fn(
            self.params,
            self.refiner_params,
            jnp.asarray(image),
            jnp.asarray(exemplars),
        )

    def predict_multi_exemplar(self, image, exemplars) -> dict:
        """Reference multi-exemplar eval (trainer.py:75-121): independent
        per-exemplar passes, detections concatenated, single NMS over the
        union. image (1, S, S, 3); exemplars (K, 4)."""
        parts = [
            self(image, np.asarray(ex, np.float32)[None, None, :])
            for ex in np.asarray(exemplars).reshape(-1, 4)
        ]
        merged = {
            k: jnp.concatenate([p[k] for p in parts], axis=1)
            for k in ("boxes", "scores", "refs", "valid")
        }
        if self._nms_fn is None:
            iou = self.cfg.NMS_iou_threshold
            self._nms_fn = jax.jit(lambda d: batched_nms(d, iou))
        return self._nms_fn(merged)


def detections_to_numpy(dets: dict) -> list:
    """Fixed-slot device detections -> per-image ragged numpy dicts
    (the reference's pred_logits/pred_boxes/ref_points lists)."""
    boxes = np.asarray(dets["boxes"])
    scores = np.asarray(dets["scores"])
    refs = np.asarray(dets["refs"])
    valid = np.asarray(dets["valid"])
    out = []
    for b in range(boxes.shape[0]):
        v = valid[b]
        out.append(
            {"boxes": boxes[b][v], "scores": scores[b][v], "refs": refs[b][v]}
        )
    return out
