"""End-to-end inference: one jitted program per (image-size, template) bucket.

Covers the reference's eval/demo inference paths:
- trainer.py each_step test branch (:143-150): forward -> Get_pred_boxes ->
  [refine] -> NMS;
- each_step_multi_exemplars (:75-121): per-exemplar forward + decode, concat,
  one NMS over the union;
- demo.py Inference.infer (:102-132).

The whole chain — encoder, template match, heads, peak decode, NMS — is ONE
XLA program (the fused-inference north star of BASELINE.json). Dynamic shape
sources (input resolution 1024/1536, template size) become a small set of
host-selected static buckets, each compiled once and cached.
"""

from __future__ import annotations

import functools

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import flax.linen as nn

from tmr_tpu.models import build_model
from tmr_tpu.models.matching_net import select_capacity_bucket
from tmr_tpu.obs import track_compile, track_devtime
from tmr_tpu.ops.postprocess import (
    batched_nms,
    compact_detections,
    decode_detections,
    device_tail_ok,
)

#: legal TMR_DECODE_TAIL values (config registry imports this)
DECODE_TAIL_MODES = ("host", "device")


def decode_tail_mode() -> str:
    """Resolve TMR_DECODE_TAIL at trace time. "device" is admitted only
    through the ops/postprocess.device_tail_ok self-check — a refusal
    records its gate_probe/v1 cause and runs the host path, never a
    silent reorder."""
    import os

    mode = os.environ.get("TMR_DECODE_TAIL", "host")
    if mode not in DECODE_TAIL_MODES:
        raise ValueError(
            f"TMR_DECODE_TAIL={mode!r}: expected "
            + "|".join(DECODE_TAIL_MODES)
        )
    if mode == "device" and not device_tail_ok():
        import warnings

        from tmr_tpu.diagnostics import FormulationFallbackWarning

        warnings.warn(FormulationFallbackWarning(
            "TMR_DECODE_TAIL",
            "TMR_DECODE_TAIL=device: compaction self-check refused; "
            "running the host decode tail"
        ))
        return "host"
    return mode


class _PassthroughBackbone(nn.Module):
    """Stand-in backbone for head-only programs fed precomputed features."""

    @nn.compact
    def __call__(self, x):
        return x


class Predictor:
    """Bucketed-jit inference wrapper around MatchingNet.

    With ``refiner`` set (and cfg.refine_box), the pipeline becomes
    forward -> decode -> SAM box refinement -> NMS, the reference test-step
    order (trainer.py:143-150) — still one fused XLA program. The refiner
    consumes the model's own pre-upsample backbone features instead of the
    reference's second ViT-H pass (trainer.py:146-147).
    """

    def __init__(self, cfg, params=None, model=None, refiner=None,
                 refiner_params=None):
        self.cfg = cfg
        self.model = model if model is not None else build_model(cfg)
        self.params = params
        self.refiner = refiner
        self.refiner_params = refiner_params
        self._compiled: Dict[tuple, callable] = {}
        #: (params identity, QuantizedParams|None) — the resolved int8
        #: storage state for the CURRENT param tree (TMR_QUANT_STORAGE)
        self._storage_cache: Optional[tuple] = None

    def invalidate_compiled(self, kinds=None) -> int:
        """Drop compiled programs so the next call re-traces under the
        current env knobs — the live-autotune hot-swap hook
        (autotune_live.apply_winner): a promoted formulation takes
        effect without a restart, paying exactly the re-traces its knob
        scope requires.

        ``kinds`` is None for everything (formulation knobs every
        program embeds — attention impls, quant numerics; the int8
        storage cache drops too so TMR_QUANT_STORAGE re-resolves), or an
        iterable of program kinds ("single", "multi", "multi_batched",
        "backbone", "heads", "gallery", "gallery_heads") matching the
        ``_compiled`` key convention: keys lead with their kind string
        except the single-image program, whose key leads with the int
        capacity. Returns the number of programs dropped."""
        if kinds is None:
            n = len(self._compiled)
            self._compiled.clear()
            self._storage_cache = None
            return n
        wanted = set(kinds)
        drop = [
            key for key in self._compiled
            if (key[0] if isinstance(key[0], str) else "single") in wanted
        ]
        for key in drop:
            del self._compiled[key]
        return len(drop)

    # ------------------------------------------------------- int8 storage
    def _storage_state(self):
        """The offline-quantized param tree for TMR_QUANT_STORAGE=int8,
        or None (knob off / params unset / admission refused — refusals
        record gate_probe/v1 causes, see quant.stored_params_for).

        Materialized once per (process, checkpoint digest) and cached
        per param-tree identity here, so a second Predictor over the
        same weights assembles from the digest cache instead of
        re-quantizing. The compiled programs then RECEIVE the int8
        arrays (HBM weight bytes for those leaves drop 4x) and every
        program key carries the digest — a checkpoint swap can never
        reuse a program compiled against other scales."""
        from tmr_tpu.ops import quant as _q

        if self.params is None or _q.quant_storage_mode() != "int8":
            return None
        if getattr(self.model, "quant_storage", None) is None:
            # non-MatchingNet models have no stored-tail formulation
            return None
        cached = self._storage_cache
        if cached is not None and cached[0] is self.params:
            return cached[1]
        hw = self.feature_hw(int(self.cfg.image_size))
        c_cat = (self.cfg.emb_dim * 2 if self.cfg.fusion
                 else self.cfg.emb_dim)
        st = _q.stored_params_for(
            self.params, hw, hw, c_cat, c_cat,
            self.cfg.decoder_num_layer, self.cfg.decoder_kernel_size,
            dtype_name=self.cfg.compute_dtype,
            box_reg=self.cfg.box_reg,
        )
        self._storage_cache = (self.params, st)
        return st

    def exec_params(self):
        """The param tree the compiled programs consume: the stored int8
        tree under an admitted TMR_QUANT_STORAGE=int8, else
        ``self.params`` unchanged. The serving layer stages THIS tree
        (serve/engine.py), so serve-side weight traffic drops with it
        (4x for the quantized leaves)."""
        st = self._storage_state()
        return st.tree if st is not None else self.params

    def quant_stamp(self) -> Optional[dict]:
        """Provenance for stats()/health()/serve_report: which quant
        mode + storage the programs run, or None when fully exact."""
        from tmr_tpu.ops.quant import quant_mode

        st = self._storage_state()
        if st is not None:
            return st.stamp()
        if quant_mode() == "int8":
            return {"mode": "int8", "storage": "off"}
        return None

    def feature_stamp(self) -> tuple:
        """Cache-key provenance for extracted backbone features:
        ``(param-tree digest, backbone formulation)``. Every serving
        feature-cache key carries this tuple alongside the image digest,
        so a checkpoint swap or a storage-knob flip can never serve
        features extracted under OTHER weights (the stale-feature bug
        class the image-digest-only key allowed). The stored-int8 tree
        contributes its content digest; an f32 tree contributes its
        in-process identity — a fresh tree is a fresh identity, and the
        caches these keys feed are in-process."""
        st = self._storage_state()
        params_digest = (st.digest if st is not None
                         else f"id{id(self.params)}")
        return (params_digest, str(self.cfg.backbone))

    def _storage_model(self, model, st):
        """Clone ``model`` for a stored-tree program when storage is
        active (the flag routes MatchingNet onto the fused stored
        tail)."""
        return model.clone(quant_storage=True) if st is not None else model

    @staticmethod
    def _variables(params, scales):
        v = {"params": params}
        if scales is not None:
            v["quant_scales"] = scales
        return v

    def _storage_entry(self, run, st):
        """Caller-proofing for storage-compiled programs: the direct
        consumers of ``_compiled`` entries (bench.py, bench_extra,
        profile_breakdown, …) historically pass ``predictor.params``;
        under TMR_QUANT_STORAGE=int8 the program needs the stored int8
        tree instead. This wrapper swaps the tree when the caller passed
        EXACTLY ``self.params`` (identity — device-placed copies pass
        through untouched); any other f32 tree still fails the trace
        loudly via the int8-dtype check in fused_heads._maybe_quant,
        never silently dequantizing unquantized weights."""
        if st is None:
            return run

        def swapped(params, *args, **kw):
            if params is self.params:
                params = st.tree
            return run(params, *args, **kw)

        swapped.__wrapped__ = run
        return swapped

    def init_params(self, seed: int = 0, image_size: Optional[int] = None):
        s = image_size or self.cfg.image_size
        image = jnp.zeros((1, s, s, 3), jnp.float32)
        exemplars = jnp.array([[[0.4, 0.4, 0.6, 0.6]]], jnp.float32)
        # jit the init: eager init dispatches thousands of tiny ops, which
        # is pathologically slow over a remote-device tunnel
        self.params = jax.jit(self.model.init)(
            jax.random.key(seed), image, exemplars
        )["params"]
        return self.params

    def feature_hw(self, image_size: int) -> int:
        bb = self.model.backbone
        stride = getattr(bb, "feature_stride", None) or getattr(
            bb, "patch_size", 16
        )
        base = image_size // stride
        return base * 2 if self.cfg.feature_upsample else base

    def _decode(self, out: dict, exemplars: jnp.ndarray) -> dict:
        """Peak-pick + decode model outputs into fixed detection slots
        (shared by the single- and multi-exemplar programs)."""
        cfg = self.cfg
        return decode_detections(
            out["objectness"],
            out["regressions"],
            exemplars,
            cls_threshold=cfg.NMS_cls_threshold,
            max_detections=cfg.max_detections,
            box_reg=cfg.box_reg,
            scale_imgsize=cfg.regression_scaling_imgsize,
            scale_wh_only=cfg.regression_scaling_WH_only,
        )

    def _refine_nms(self, dets: dict, feature, image_hw, refiner_params,
                    refine: bool) -> dict:
        """[refine ->] NMS tail (reference test-step order trainer.py:143-150,
        shared by the single- and multi-exemplar programs). Under
        TMR_DECODE_TAIL=device the survivors are additionally compacted to
        the leading slots on device with a ``count`` vector
        (ops/postprocess.compact_detections) — same fixed output shape,
        host postprocess becomes a prefix slice instead of a 2000-slot
        boolean scan, per-image results bitwise-identical to the host
        path (tests/test_decode_tail.py)."""
        if refine:
            dets = self.refiner.refine(
                refiner_params, feature, dets, image_hw
            )
        dets = batched_nms(dets, self.cfg.NMS_iou_threshold)
        if decode_tail_mode() == "device":
            dets = compact_detections(dets)
        return dets

    def _single_pipeline(self, model, refine: bool, scales=None):
        """The ONE traced body of the fused single-exemplar program:
        forward -> decode -> [refine] -> NMS. Both the plain jit
        (:meth:`_get_fn`) and the mesh-sharded variants
        (:meth:`_get_sharded_fn`) close over this exact function — the
        dp bitwise-parity contract depends on the two programs tracing
        the identical op sequence, so there must never be a second
        copy to drift. ``scales`` (storage mode) is the offline
        quant_scales collection, closed over as trace-time constants —
        tiny, and the program key carries the tree digest. Returns
        ``(dets, model_out)`` (the loss path consumes ``model_out``;
        other callers drop it)."""

        def body(params, refiner_params, image, exemplars):
            out = model.apply(self._variables(params, scales), image,
                              exemplars)
            dets = self._decode(out, exemplars[:, 0, :])
            dets = self._refine_nms(
                dets, out["backbone_feature"],
                (image.shape[1], image.shape[2]), refiner_params, refine,
            )
            return dets, out

        return body

    def _multi_batched_pipeline(self, model, heads, k_bucket: int,
                                refine: bool, scales=None):
        """The ONE traced body of the batched union-NMS program (see
        :meth:`_single_pipeline` for why it is shared between the plain
        and mesh-sharded builders)."""

        def body(params, refiner_params, image, exemplars, k_real):
            b = image.shape[0]
            feat = model.backbone.apply(
                {"params": params["backbone"]}, image
            )
            if isinstance(feat, (list, tuple)):
                if len(feat) != 1:
                    raise NotImplementedError(
                        "fused multi-exemplar inference supports single-"
                        "level backbones only (every shipped backbone is)"
                    )
                feat = feat[0]
            head_params = {n: v for n, v in params.items()
                           if n != "backbone"}
            out = heads.apply(
                self._variables(head_params, scales),
                jnp.repeat(feat, k_bucket, axis=0),  # image-major (B*k,)
                exemplars.reshape(b * k_bucket, 1, 4),
            )
            dets = self._decode(out, exemplars.reshape(b * k_bucket, 4))
            row_ok = jnp.arange(k_bucket)[None, :] < k_real[:, None]
            dets["valid"] = dets["valid"] & row_ok.reshape(-1)[:, None]
            merged = {
                name: dets[name].reshape((b, -1) + dets[name].shape[2:])
                for name in ("boxes", "scores", "refs", "valid")
            }
            return self._refine_nms(
                merged, feat, (image.shape[1], image.shape[2]),
                refiner_params, refine,
            )

        return body

    def _get_fn(self, capacity: int, loss_fn=None,
                chain_feedback: bool = False, donate: bool = False):
        """Compiled forward -> decode -> [refine] -> NMS program for one
        template-capacity bucket.

        ``donate=True`` donates the staged image buffer to the program
        (``donate_argnums``): the serving layer's H2D staging buffers are
        single-use, so XLA may alias them for scratch/output instead of
        holding both live — only meaningful on backends that implement
        donation (TPU/GPU; XLA:CPU ignores it with a warning, so the serve
        engine requests it only there).

        With ``loss_fn(model_out, exemplars, *extra) -> losses`` the program
        additionally returns losses computed from the SAME forward — the
        trainer's eval step (the reference's each_step computes loss and
        Get_pred_boxes from one forward, trainer.py:123-153) — and the
        returned callable takes the extra loss inputs after ``exemplars``.

        ``chain_feedback=True`` is the benchmark hook: the callable takes a
        trailing scalar that is added to the image INSIDE the program and
        returns ``(dets, scalar)``, so chained timing loops execute
        back-to-back on device while measuring this exact production
        program (bench.py / scripts/bench_extra.py).

        There is exactly one copy of this pipeline; every consumer
        (inference, trainer eval, the benchmarks) compiles through it.
        """
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        # int() the capacity: a numpy-int bucket (e.g. derived from array
        # geometry by a caller) must land on the same compiled entry as the
        # equal Python int — tuple keys compare equal but a second jit
        # wrapper per int flavor would silently recompile
        capacity = int(capacity)
        # storage mode forks the key on the checkpoint digest: the
        # program closes over that tree's scales, so a param swap (new
        # digest) must compile a new entry, never reuse stale scales
        st = self._storage_state()
        key = (capacity, refine, loss_fn, chain_feedback, donate) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        jit = (
            functools.partial(jax.jit, donate_argnums=(2,)) if donate
            else jax.jit
        )

        body = self._single_pipeline(
            model, refine, scales=st.scales if st is not None else None
        )

        @jit
        def run(params, refiner_params, image, exemplars, *extra):
            if chain_feedback:
                image = image + extra[-1]
                extra = extra[:-1]
            dets, out = body(params, refiner_params, image, exemplars)
            fb = jnp.sum(dets["scores"]) * 0.0
            if loss_fn is not None:
                dets = (loss_fn(out, exemplars, *extra), dets)
            if chain_feedback:
                return dets, fb
            return dets

        # compile-event accounting (obs/compile.py): the first call of
        # every fresh cache entry records (key, wall, cold|key-change) —
        # recompile storms become visible events instead of latency
        # cliffs. The devtime wrapper outside it (obs/devtime.py) is the
        # flight recorder's per-execution device-time attribution seam;
        # with TMR_FLIGHT=0 (default) it is one bool check.
        run = self._storage_entry(track_devtime(
            track_compile(run, "single", key,
                          bucket={"capacity": capacity}),
            "single", key, bucket={"capacity": capacity},
        ), st)
        self._compiled[key] = run
        return run

    def pick_capacity(self, exemplars: np.ndarray, image_size: int) -> int:
        """Host-side template bucket for a batch: the largest per-exemplar
        need. Always a Python int (numpy ints from array-derived geometry
        must not fork the ``_compiled`` key space)."""
        hw = self.feature_hw(int(image_size))
        need = 1
        for ex in np.asarray(exemplars).reshape(-1, 4):
            need = max(
                need,
                select_capacity_bucket(ex, hw, hw, self.cfg.template_buckets),
            )
        return int(need)

    def bucket_key(self, image_size: int, exemplars,
                   multi: bool = False, k_real: Optional[int] = None
                   ) -> Tuple[str, int, int, int]:
        """The static-program bucket a request compiles into, as one
        hashable tuple — the serving layer's coalescing key.

        Returns ``("single", image_size, capacity, K)`` for the
        ``__call__`` path (K = exemplar slots carried per image; the
        matcher consumes slot 0) or ``("multi", image_size, capacity,
        k_bucket)`` for the union-NMS multi-exemplar path. Requests with
        equal keys batch into one jitted program; every element is a
        Python int (see :meth:`pick_capacity`)."""
        image_size = int(image_size)
        exemplars = np.asarray(exemplars, np.float32).reshape(-1, 4)
        if multi:
            k = int(k_real) if k_real is not None else len(exemplars)
            cap = self.pick_capacity(exemplars[:k], image_size)
            k_bucket = int(next((b for b in self.K_BUCKETS if b >= k), k))
            return ("multi", image_size, cap, k_bucket)
        # __call__ sizes the template bucket from every carried slot
        # (pick_capacity over the full (K, 4)) — mirror it exactly so a
        # batched-serve request compiles into the same-capacity program as
        # the sequential call it must match bitwise
        return ("single", image_size, self.pick_capacity(exemplars,
                                                         image_size),
                len(exemplars))

    def __call__(self, image, exemplars) -> dict:
        """image (B, S, S, 3) float32 normalized; exemplars (B, K, 4).
        Returns dict boxes/scores/refs/valid as fixed-shape device arrays."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        cap = self.pick_capacity(exemplars, int(image.shape[1]))
        fn = self._get_fn(cap)
        return fn(
            self.exec_params(),
            self.refiner_params,
            jnp.asarray(image),
            jnp.asarray(exemplars),
        )

    #: static exemplar-count buckets for the multi-exemplar program: the
    #: compiled fn is keyed by bucket, real counts pad up and padded rows'
    #: detections are masked out — variable per-image exemplar counts
    #: (FSCD-LVIS) don't trigger a full recompile each. The paper's
    #: contract is k <= 3; the 16/32 power-of-two rungs exist for the
    #: gallery tier (serve/gallery.py), where a standing pattern set's
    #: union of boxes rides the same ladder — without them every distinct
    #: k past 8 fell through to its own compiled program (a recompile per
    #: ragged count, pinned against by tests/test_gallery.py).
    K_BUCKETS = (1, 2, 3, 4, 6, 8, 16, 32)

    #: static entry-count buckets for the fused gallery programs: N bank
    #: entries pad up to a rung and mask with ``n_real`` exactly like the
    #: k ladder — ragged bank sizes inside one rung never recompile. The
    #: serving-side ladder cap is autotune-elected like the batch bound
    #: (utils/autotune.measured_gallery_nmax).
    N_BUCKETS = (1, 2, 4, 8, 16, 32)

    def _get_multi_fn(self, capacity: int, k_bucket: int, loss_fn=None):
        """One fused program for K-exemplar inference: encoder ONCE, then the
        matcher/decode pipeline batched over the K exemplars, union NMS.

        The reference runs a full forward per exemplar and one union NMS at
        the end (trainer.py:75-121: per-exemplar Get_pred_boxes with NO
        per-exemplar NMS, concat, [refine], NMS — demo.py:111-132 likewise),
        recomputing the frozen encoder K times. Here the encoder output is
        broadcast to a K-batch for the heads — identical numerics (the
        encoder is deterministic), ~K x fewer encoder FLOPs, one dispatch.

        ``loss_fn(out_k, exemplar_k, *extra) -> losses`` computes one
        exemplar's losses from its B=1 slice of the heads output; the
        program vmaps it over the K axis, masks padded rows, and returns the
        SUM over real exemplars — the reference's multi-exemplar loss
        semantics (trainer.py:102-104,121 sums per-exemplar losses).
        """
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        # int-normalized key: a numpy-int capacity/k_bucket (callers deriving
        # them from array shapes) must hit the same compiled entry as the
        # equal Python int instead of silently recompiling
        capacity, k_bucket = int(capacity), int(k_bucket)
        st = self._storage_state()
        key = ("multi", capacity, k_bucket, refine, loss_fn) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        heads = model.clone(backbone=_PassthroughBackbone())
        scales = st.scales if st is not None else None

        @jax.jit
        def run(params, refiner_params, image, exemplars, k_real, *extra):
            # image (1, S, S, 3); exemplars (k_bucket, 4); k_real () int32
            feat = model.backbone.apply(
                {"params": params["backbone"]}, image
            )
            if isinstance(feat, (list, tuple)):
                if len(feat) != 1:
                    raise NotImplementedError(
                        "fused multi-exemplar inference supports single-"
                        "level backbones only (every shipped backbone is)"
                    )
                feat = feat[0]
            head_params = {n: v for n, v in params.items() if n != "backbone"}
            out = heads.apply(
                self._variables(head_params, scales),
                jnp.repeat(feat, k_bucket, axis=0),
                exemplars[:, None, :],
            )
            dets = self._decode(out, exemplars)
            # mask padded exemplar rows, then concat the K per-exemplar slot
            # sets into one image's union
            row_ok = jnp.arange(k_bucket) < k_real
            dets["valid"] = dets["valid"] & row_ok[:, None]
            merged = {
                name: dets[name].reshape((1, -1) + dets[name].shape[2:])
                for name in ("boxes", "scores", "refs", "valid")
            }
            final = self._refine_nms(
                merged, feat, (image.shape[1], image.shape[2]),
                refiner_params, refine,
            )
            if loss_fn is None:
                return final

            def one_exemplar_losses(obj_k, reg_k, ex_k):
                out_k = {
                    "objectness": [o[None] for o in obj_k],
                    # None levels = box regression ablated (matching_net)
                    "regressions": [
                        r[None] if r is not None else None for r in reg_k
                    ],
                }
                return loss_fn(out_k, ex_k[None, None, :], *extra)

            per_k = jax.vmap(one_exemplar_losses)(
                [o for o in out["objectness"]],
                [r for r in out["regressions"]],
                exemplars,
            )
            losses = jax.tree.map(
                lambda v: jnp.where(row_ok, v, 0.0).sum(), per_k
            )
            return losses, final

        run = self._storage_entry(track_devtime(
            track_compile(run, "multi", key,
                          bucket={"capacity": capacity,
                                  "k_bucket": k_bucket}),
            "multi", key, bucket={"capacity": capacity,
                                  "k_bucket": k_bucket},
        ), st)
        self._compiled[key] = run
        return run

    def predict_multi_exemplar(self, image, exemplars, loss_fn=None,
                               loss_args=(), k_real=None):
        """Reference multi-exemplar eval (trainer.py:75-121): per-exemplar
        decode, concatenated, single NMS over the union. image (1, S, S, 3);
        exemplars (K, 4). With ``loss_fn`` (see _get_multi_fn) returns
        (losses summed over exemplars, dets); else just dets.

        ``k_real`` marks how many leading exemplar rows are real when the
        caller hands over a pre-padded array (the serving layer does); rows
        past it are ignored. Any integer flavor is accepted — the bucket
        key is int-normalized, so a numpy-int ``k_real`` can never fork
        ``_compiled`` into a recompile (pinned by tests/test_serve.py)."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        exemplars = np.asarray(exemplars, np.float32).reshape(-1, 4)
        k = int(k_real) if k_real is not None else len(exemplars)
        if not 1 <= k <= len(exemplars):
            raise ValueError(
                f"k_real={k} out of range for {len(exemplars)} exemplar rows"
            )
        exemplars = exemplars[:k]
        k_bucket = int(next((b for b in self.K_BUCKETS if b >= k), k))
        pad = np.tile(exemplars[-1:], (k_bucket - k, 1))  # masked below
        cap = self.pick_capacity(exemplars, int(image.shape[1]))
        fn = self._get_multi_fn(cap, k_bucket, loss_fn=loss_fn)
        return fn(
            self.exec_params(),
            self.refiner_params,
            jnp.asarray(image),
            jnp.asarray(np.concatenate([exemplars, pad], axis=0)),
            jnp.asarray(k, jnp.int32),
            *loss_args,
        )


    # ---------------------------------------------------------------- serve
    # Batched entry points for the throughput serving layer (tmr_tpu/serve):
    # the batcher coalesces single-image requests into these fixed-(B, K)
    # programs, pads ragged tails, and unpads per request. They reuse the
    # exact _decode/_refine_nms pipeline, so serve results stay the
    # production numerics.

    def _get_multi_batched_fn(self, capacity: int, k_bucket: int,
                              donate: bool = False):
        """The B>1 generalization of :meth:`_get_multi_fn`: encoder once per
        image, heads batched over B*k_bucket exemplar rows, one union NMS
        per image. image (B, S, S, 3); exemplars (B, k_bucket, 4); k_real
        (B,) int32 — each image masks its own padded rows, so a batch can
        mix real exemplar counts inside one k bucket. The B=1 slice traces
        the same op sequence as ``_get_multi_fn``."""
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity, k_bucket = int(capacity), int(k_bucket)
        st = self._storage_state()
        key = ("multi_batched", capacity, k_bucket, refine, donate) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        heads = model.clone(backbone=_PassthroughBackbone())
        jit = (
            functools.partial(jax.jit, donate_argnums=(2,)) if donate
            else jax.jit
        )
        run = jit(self._multi_batched_pipeline(
            model, heads, k_bucket, refine,
            scales=st.scales if st is not None else None,
        ))
        run = self._storage_entry(track_devtime(
            track_compile(run, "multi_batched", key,
                          bucket={"capacity": capacity,
                                  "k_bucket": k_bucket}),
            "multi_batched", key, bucket={"capacity": capacity,
                                          "k_bucket": k_bucket},
        ), st)
        self._compiled[key] = run
        return run

    def predict_multi_batch(self, images, exemplars, k_real,
                            donate: bool = False) -> dict:
        """Batched union-NMS inference: images (B, S, S, 3), exemplars
        (B, k_bucket, 4) pre-padded to one k bucket, k_real (B,) real row
        counts. Returns fixed-slot dets with leading dim B."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        exemplars = jnp.asarray(exemplars)
        fn = self._get_multi_batched_fn(
            self.pick_capacity(exemplars, int(images.shape[1])),
            int(exemplars.shape[1]), donate=donate,
        )
        return fn(
            self.exec_params(), self.refiner_params, jnp.asarray(images),
            exemplars, jnp.asarray(k_real, jnp.int32),
        )

    def _get_backbone_fn(self):
        """Encoder-only program: image (B, S, S, 3) -> pre-upsample backbone
        features (B, h, w, C) — the tensor the serving layer's image-feature
        cache stores, and exactly what :meth:`_get_heads_fn` consumes."""
        key = ("backbone",)
        if key in self._compiled:
            return self._compiled[key]

        @jax.jit
        def run(params, image):
            f = self.model.backbone.apply({"params": params["backbone"]},
                                          image)
            if isinstance(f, (list, tuple)):
                if len(f) != 1:
                    raise NotImplementedError(
                        "feature-cached serving supports single-level "
                        "backbones only (every shipped backbone is)"
                    )
                f = f[0]
            return f

        run = track_devtime(track_compile(run, "backbone", key),
                            "backbone", key)
        self._compiled[key] = run
        return run

    def _get_heads_fn(self, capacity: int, image_size: int):
        """Heads-on-precomputed-features program for one capacity bucket:
        features (B, h, w, C) from :meth:`_get_backbone_fn` -> the same
        upsample/proj/match/decode/[refine]/NMS tail as ``_get_fn``.

        Feature-cache hits skip the encoder (the dominant cost) through
        this program. Because the tail compiles as its OWN XLA program
        here, its outputs can differ from the fused single program at the
        last-ULP level (different fusion decisions); the serving layer
        documents this and keeps the bitwise-exactness contract on the
        fused path only."""
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity, image_size = int(capacity), int(image_size)
        st = self._storage_state()
        key = ("heads", capacity, image_size, refine) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        scales = st.scales if st is not None else None

        @jax.jit
        def run(params, refiner_params, features, exemplars):
            out = model.apply(
                self._variables(params, scales),
                jnp.zeros((features.shape[0], 1, 1, 3), jnp.float32),
                exemplars, features=features,
            )
            dets = self._decode(out, exemplars[:, 0, :])
            return self._refine_nms(
                dets, out["backbone_feature"], (image_size, image_size),
                refiner_params, refine,
            )

        run = self._storage_entry(track_devtime(
            track_compile(run, "heads", key,
                          bucket={"capacity": capacity,
                                  "image_size": image_size}),
            "heads", key, bucket={"capacity": capacity,
                                  "image_size": image_size},
        ), st)
        self._compiled[key] = run
        return run

    # -------------------------------------------------------------- gallery
    # Template-bank programs for the gallery tier (tmr_tpu/serve/gallery):
    # a STANDING pattern set of N registered exemplar sets matched against
    # a stream frame with ONE backbone pass, then the matcher/heads/decode
    # tail batched over N*k rows and a union NMS PER ENTRY — the
    # multi-pattern generalization of _get_multi_fn. Entry i's slice
    # traces the same op sequence as predict_multi_exemplar on that
    # entry's exemplars, which is what keeps the fused gallery arm
    # bitwise-identical to the N-loop (tests/test_gallery.py pins it;
    # the same batch-invariance caveat as test_serve applies under the
    # forced-8-device CPU conftest). N pads to an N_BUCKETS rung with
    # ``n_real`` masking exactly like the k ladder.

    def _gallery_tail(self, heads, n_bucket: int, k_bucket: int,
                      refine: bool, scales=None):
        """The ONE traced tail of the gallery programs: heads over
        ``n_bucket * k_bucket`` exemplar rows against one frame's
        features, per-entry row masking, per-entry union NMS. Shared by
        the fused (:meth:`_get_gallery_fn`) and heads-split
        (:meth:`_get_gallery_heads_fn`) builders so the two arms can
        never drift — the split arm differs only in where the features
        come from (the documented heads-path ULP exception)."""

        def tail(params, refiner_params, feat, exemplars, k_real, n_real,
                 image_hw):
            # feat (1, h, w, C); exemplars (n_bucket, k_bucket, 4);
            # k_real (n_bucket,) int32; n_real () int32
            head_params = {n: v for n, v in params.items()
                           if n != "backbone"}
            rows = n_bucket * k_bucket
            out = heads.apply(
                self._variables(head_params, scales),
                jnp.repeat(feat, rows, axis=0),
                exemplars.reshape(rows, 1, 4),
            )
            dets = self._decode(out, exemplars.reshape(rows, 4))
            row_ok = jnp.arange(k_bucket)[None, :] < k_real[:, None]
            entry_ok = (jnp.arange(n_bucket) < n_real)[:, None]
            dets["valid"] = dets["valid"] & (
                (row_ok & entry_ok).reshape(-1)[:, None]
            )
            merged = {
                name: dets[name].reshape(
                    (n_bucket, -1) + dets[name].shape[2:]
                )
                for name in ("boxes", "scores", "refs", "valid")
            }
            feature = (jnp.repeat(feat, n_bucket, axis=0) if refine
                       else feat)
            return self._refine_nms(merged, feature, image_hw,
                                    refiner_params, refine)

        return tail

    def _get_gallery_fn(self, capacity: int, n_bucket: int, k_bucket: int,
                        donate: bool = False):
        """The FUSED gallery program: frame image in, backbone ONCE,
        then :meth:`_gallery_tail` over the bank — the cold-frame arm
        whose per-entry results are bitwise the N-loop of
        ``predict_multi_exemplar``. image (1, S, S, 3); exemplars
        (n_bucket, k_bucket, 4); k_real (n_bucket,); n_real () int32.
        Returns fixed-slot dets with leading dim n_bucket (entry
        order)."""
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity, n_bucket, k_bucket = (
            int(capacity), int(n_bucket), int(k_bucket)
        )
        st = self._storage_state()
        key = ("gallery", capacity, n_bucket, k_bucket, refine, donate) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        heads = model.clone(backbone=_PassthroughBackbone())
        tail = self._gallery_tail(
            heads, n_bucket, k_bucket, refine,
            scales=st.scales if st is not None else None,
        )
        jit = (
            functools.partial(jax.jit, donate_argnums=(2,)) if donate
            else jax.jit
        )

        @jit
        def run(params, refiner_params, image, exemplars, k_real, n_real):
            feat = model.backbone.apply(
                {"params": params["backbone"]}, image
            )
            if isinstance(feat, (list, tuple)):
                if len(feat) != 1:
                    raise NotImplementedError(
                        "gallery inference supports single-level "
                        "backbones only (every shipped backbone is)"
                    )
                feat = feat[0]
            return tail(params, refiner_params, feat, exemplars, k_real,
                        n_real, (image.shape[1], image.shape[2]))

        bucket = {"capacity": capacity, "n_bucket": n_bucket,
                  "k_bucket": k_bucket}
        run = self._storage_entry(track_devtime(
            track_compile(run, "gallery", key, bucket=bucket),
            "gallery", key, bucket=bucket,
        ), st)
        self._compiled[key] = run
        return run

    def _get_gallery_heads_fn(self, capacity: int, n_bucket: int,
                              k_bucket: int, image_size: int):
        """Gallery tail on PRECOMPUTED features (the feature-cache /
        prefilter arm): features (1, h, w, C) from
        :meth:`_get_backbone_fn`. Same tail as the fused program —
        compiled as its own XLA program, so the heads-path last-ULP
        exception applies (cold gallery traffic stays on the fused
        bitwise arm)."""
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity, n_bucket, k_bucket, image_size = (
            int(capacity), int(n_bucket), int(k_bucket), int(image_size)
        )
        st = self._storage_state()
        key = ("gallery_heads", capacity, n_bucket, k_bucket, image_size,
               refine) + ((st.digest,) if st is not None else ())
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        heads = model.clone(backbone=_PassthroughBackbone())
        tail = self._gallery_tail(
            heads, n_bucket, k_bucket, refine,
            scales=st.scales if st is not None else None,
        )

        @jax.jit
        def run(params, refiner_params, features, exemplars, k_real,
                n_real):
            return tail(params, refiner_params, features, exemplars,
                        k_real, n_real, (image_size, image_size))

        bucket = {"capacity": capacity, "n_bucket": n_bucket,
                  "k_bucket": k_bucket, "image_size": image_size}
        run = self._storage_entry(track_devtime(
            track_compile(run, "gallery_heads", key, bucket=bucket),
            "gallery_heads", key, bucket=bucket,
        ), st)
        self._compiled[key] = run
        return run

    def _get_gallery_prefilter_fn(self, n_bucket: int, k_bucket: int):
        """Coarse prefilter program: channel-pooled low-res correlation
        score per bank entry (ops/xcorr.coarse_prefilter_scores) on the
        frame's backbone features — the cheap ranking stage that decides
        which entries earn the full match+decode. Parameter-free; one
        compiled entry per (n_bucket, k_bucket)."""
        from tmr_tpu.ops.xcorr import coarse_prefilter_scores

        n_bucket, k_bucket = int(n_bucket), int(k_bucket)
        key = ("gallery_prefilter", n_bucket, k_bucket)
        if key in self._compiled:
            return self._compiled[key]

        @jax.jit
        def run(features, exemplars, k_real, n_real):
            return coarse_prefilter_scores(features, exemplars, k_real,
                                           n_real)

        bucket = {"n_bucket": n_bucket, "k_bucket": k_bucket}
        run = track_devtime(
            track_compile(run, "gallery_prefilter", key, bucket=bucket),
            "gallery_prefilter", key, bucket=bucket,
        )
        self._compiled[key] = run
        return run

    def predict_gallery(self, image, exemplars, k_real, n_real=None,
                        features=None, image_size=None) -> dict:
        """Match a bank of N exemplar sets against ONE frame: image
        (1, S, S, 3); exemplars (N, k_bucket, 4) pre-padded to one k
        rung; k_real (N,) real row counts; ``n_real`` marks how many
        leading entries are real (the rest are rung padding). With
        ``features`` ((1, h, w, C) from :meth:`_get_backbone_fn`, plus
        ``image_size``) the encoder is skipped — the feature-cache arm.
        Returns fixed-slot dets with leading dim = the padded N rung;
        rows past ``n_real`` are fully masked."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        exemplars = np.asarray(exemplars, np.float32)
        if exemplars.ndim != 3 or exemplars.shape[-1] != 4:
            raise ValueError(
                f"expected (N, k_bucket, 4) exemplars, got "
                f"{exemplars.shape}"
            )
        n = int(n_real) if n_real is not None else exemplars.shape[0]
        if not 1 <= n <= exemplars.shape[0]:
            raise ValueError(
                f"n_real={n} out of range for {exemplars.shape[0]} "
                "bank entries"
            )
        k_real = np.asarray(k_real, np.int32).reshape(-1)
        if k_real.shape[0] != exemplars.shape[0]:
            raise ValueError("k_real must have one count per entry")
        k_bucket = int(exemplars.shape[1])
        if not all(1 <= int(k) <= k_bucket for k in k_real[:n]):
            raise ValueError(
                f"k_real rows must lie in [1, {k_bucket}]"
            )
        n_bucket = int(next((b for b in self.N_BUCKETS if b >= n), n))
        if exemplars.shape[0] < n_bucket:
            pad = n_bucket - exemplars.shape[0]
            exemplars = np.concatenate(
                [exemplars, np.tile(exemplars[-1:], (pad, 1, 1))], axis=0
            )
            k_real = np.concatenate(
                [k_real, np.ones((pad,), np.int32)]
            )
        else:
            exemplars = exemplars[:n_bucket]
            k_real = k_real[:n_bucket]
        if features is None:
            size = int(image.shape[1])
        else:
            if image_size is None:
                raise ValueError(
                    "features-arm predict_gallery needs image_size"
                )
            size = int(image_size)
        rows = np.concatenate(
            [exemplars[i, :int(k_real[i])] for i in range(n)], axis=0
        )
        cap = self.pick_capacity(rows, size)
        args = (
            self.exec_params(), self.refiner_params,
            jnp.asarray(exemplars), jnp.asarray(k_real),
            jnp.asarray(n, jnp.int32),
        )
        if features is None:
            fn = self._get_gallery_fn(cap, n_bucket, k_bucket)
            return fn(args[0], args[1], jnp.asarray(image), *args[2:])
        fn = self._get_gallery_heads_fn(cap, n_bucket, k_bucket, size)
        return fn(args[0], args[1], features, *args[2:])

    # ------------------------------------------------------- sharded serve
    # Mesh-sharded program variants for the serving tier (serve/meshplan):
    # the same _decode/_refine_nms pipeline compiled against a MeshTarget.
    # Data-parallel targets with tp == 1 go through the shard_map path of
    # parallel/compat.compile_sharded — the per-shard trace IS the
    # unsharded program body at the local batch shape, which is what
    # keeps dp-sharded serve results bitwise-identical to the unsharded
    # engine. Targets with tp > 1 go through the pjit/GSPMD path: params
    # shard Megatron-style over the group's 'tp' axis
    # (parallel/sharding.serve_param_shardings) and XLA inserts the
    # collectives — allclose-level numerics with identical keep
    # decisions (reduction reorder; the heads-path precedent).
    # Every key embeds MeshTarget.key (axis sizes + concrete device ids),
    # so a mesh-shape change compiles a NEW entry instead of silently
    # colliding with a cached program bound to other devices.

    def _sharded_shardings(self, target):
        """(params, replicated) NamedShardings for one tp target."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from tmr_tpu.parallel.sharding import serve_param_shardings

        if self.params is None:
            raise RuntimeError(
                "sharded programs need loaded params (the in_shardings "
                "tree mirrors the real param tree)"
            )
        return (
            serve_param_shardings(self.params, target.mesh),
            NamedSharding(target.mesh, P()),
        )

    def _get_sharded_fn(self, capacity: int, target, donate: bool = False):
        """Sharded variant of :meth:`_get_fn` for one
        ``serve.meshplan.MeshTarget``: mode "dp" shards the image batch
        over the mesh's dp axis, mode "group" replicates the batch and
        shards the ViT feature dims over the group's tp axis. Call
        signature and outputs match :meth:`_get_fn` (no loss/chain
        hooks — this is the serving path)."""
        from jax.sharding import PartitionSpec as P

        from tmr_tpu.parallel.compat import compile_sharded

        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity = int(capacity)
        st = self._storage_state()
        key = ("single_sharded", capacity, refine, donate, target.key) + (
            (st.digest,) if st is not None else ()
        )
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        pipeline = self._single_pipeline(
            model, refine, scales=st.scales if st is not None else None
        )

        def body(params, refiner_params, image, exemplars):
            # the SHARED single-program body (bitwise contract); the
            # sharded program drops the loss path's model_out
            return pipeline(params, refiner_params, image, exemplars)[0]

        donate_argnums = (2,) if donate else ()
        if target.mode == "dp" and target.tp == 1:
            run = compile_sharded(
                body, target.mesh,
                in_specs=(P(), P(), P("dp"), P("dp")),
                out_specs=P("dp"),
                donate_argnums=donate_argnums,
            )
        else:
            pshard, repl = self._sharded_shardings(target)
            batch = (
                self._dp_sharding(target) if target.mode == "dp" else repl
            )
            run = compile_sharded(
                body, target.mesh,
                in_shardings=(pshard, repl, batch, batch),
                out_shardings=batch,
                donate_argnums=donate_argnums,
            )
        bucket = {"capacity": capacity, "mode": target.mode,
                  "devices": target.n_devices}
        run = track_devtime(
            track_compile(run, "single_sharded", key, bucket=bucket),
            "single_sharded", key, bucket=bucket,
            devices=target.n_devices,
        )
        self._compiled[key] = run
        return run

    def _dp_sharding(self, target):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return NamedSharding(target.mesh, P("dp"))

    def _get_sharded_multi_fn(self, capacity: int, k_bucket: int, target,
                              donate: bool = False):
        """Sharded variant of :meth:`_get_multi_batched_fn` (the batched
        union-NMS program) for one MeshTarget — same masking and merge
        semantics, batch sharded over dp / params over tp per the
        target's mode."""
        from jax.sharding import PartitionSpec as P

        from tmr_tpu.parallel.compat import compile_sharded

        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        capacity, k_bucket = int(capacity), int(k_bucket)
        st = self._storage_state()
        key = ("multi_sharded", capacity, k_bucket, refine, donate,
               target.key) + ((st.digest,) if st is not None else ())
        if key in self._compiled:
            return self._compiled[key]
        model = self._storage_model(
            self.model.clone(template_capacity=capacity), st
        )
        heads = model.clone(backbone=_PassthroughBackbone())
        # the SHARED batched union-NMS body (bitwise contract)
        body = self._multi_batched_pipeline(
            model, heads, k_bucket, refine,
            scales=st.scales if st is not None else None,
        )

        donate_argnums = (2,) if donate else ()
        if target.mode == "dp" and target.tp == 1:
            run = compile_sharded(
                body, target.mesh,
                in_specs=(P(), P(), P("dp"), P("dp"), P("dp")),
                out_specs=P("dp"),
                donate_argnums=donate_argnums,
            )
        else:
            pshard, repl = self._sharded_shardings(target)
            batch = (
                self._dp_sharding(target) if target.mode == "dp" else repl
            )
            run = compile_sharded(
                body, target.mesh,
                in_shardings=(pshard, repl, batch, batch, batch),
                out_shardings=batch,
                donate_argnums=donate_argnums,
            )
        bucket = {"capacity": capacity, "k_bucket": k_bucket,
                  "mode": target.mode, "devices": target.n_devices}
        run = track_devtime(
            track_compile(run, "multi_sharded", key, bucket=bucket),
            "multi_sharded", key, bucket=bucket,
            devices=target.n_devices,
        )
        self._compiled[key] = run
        return run


def detections_to_numpy(dets: dict) -> list:
    """Fixed-slot device detections -> per-image ragged numpy dicts
    (the reference's pred_logits/pred_boxes/ref_points lists).

    Device-compacted detections (TMR_DECODE_TAIL=device: survivors in the
    leading ``count`` slots) take the prefix-slice fast path; the host
    form scans the validity mask. Both yield identical lists."""
    boxes = np.asarray(dets["boxes"])
    scores = np.asarray(dets["scores"])
    refs = np.asarray(dets["refs"])
    out = []
    if "count" in dets:
        count = np.asarray(dets["count"])
        for b in range(boxes.shape[0]):
            n = int(count[b])
            # .copy(): a prefix-slice VIEW would pin the whole padded
            # (B, max_detections, ...) batch alive for as long as the
            # caller keeps the per-image dict — the retention hazard
            # serve/engine.py's _finish documents; the host path's
            # boolean indexing below copies inherently
            out.append({"boxes": boxes[b][:n].copy(),
                        "scores": scores[b][:n].copy(),
                        "refs": refs[b][:n].copy()})
        return out
    valid = np.asarray(dets["valid"])
    for b in range(boxes.shape[0]):
        v = valid[b]
        out.append(
            {"boxes": boxes[b][v], "scores": scores[b][v], "refs": refs[b][v]}
        )
    return out
