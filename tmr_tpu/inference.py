"""End-to-end inference: one jitted program per (image-size, template) bucket.

Covers the reference's eval/demo inference paths:
- trainer.py each_step test branch (:143-150): forward -> Get_pred_boxes ->
  [refine] -> NMS;
- each_step_multi_exemplars (:75-121): per-exemplar forward + decode, concat,
  one NMS over the union;
- demo.py Inference.infer (:102-132).

The whole chain — encoder, template match, heads, peak decode, NMS — is ONE
XLA program (the fused-inference north star of BASELINE.json). Dynamic shape
sources (input resolution 1024/1536, template size) become a small set of
host-selected static buckets, each compiled once and cached.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import flax.linen as nn

from tmr_tpu.models import build_model
from tmr_tpu.models.matching_net import select_capacity_bucket
from tmr_tpu.ops.postprocess import batched_nms, decode_detections


class _PassthroughBackbone(nn.Module):
    """Stand-in backbone for head-only programs fed precomputed features."""

    @nn.compact
    def __call__(self, x):
        return x


class Predictor:
    """Bucketed-jit inference wrapper around MatchingNet.

    With ``refiner`` set (and cfg.refine_box), the pipeline becomes
    forward -> decode -> SAM box refinement -> NMS, the reference test-step
    order (trainer.py:143-150) — still one fused XLA program. The refiner
    consumes the model's own pre-upsample backbone features instead of the
    reference's second ViT-H pass (trainer.py:146-147).
    """

    def __init__(self, cfg, params=None, model=None, refiner=None,
                 refiner_params=None):
        self.cfg = cfg
        self.model = model if model is not None else build_model(cfg)
        self.params = params
        self.refiner = refiner
        self.refiner_params = refiner_params
        self._compiled: Dict[tuple, callable] = {}

    def init_params(self, seed: int = 0, image_size: Optional[int] = None):
        s = image_size or self.cfg.image_size
        image = jnp.zeros((1, s, s, 3), jnp.float32)
        exemplars = jnp.array([[[0.4, 0.4, 0.6, 0.6]]], jnp.float32)
        # jit the init: eager init dispatches thousands of tiny ops, which
        # is pathologically slow over a remote-device tunnel
        self.params = jax.jit(self.model.init)(
            jax.random.key(seed), image, exemplars
        )["params"]
        return self.params

    def feature_hw(self, image_size: int) -> int:
        bb = self.model.backbone
        stride = getattr(bb, "feature_stride", None) or getattr(
            bb, "patch_size", 16
        )
        base = image_size // stride
        return base * 2 if self.cfg.feature_upsample else base

    def _decode(self, out: dict, exemplars: jnp.ndarray) -> dict:
        """Peak-pick + decode model outputs into fixed detection slots
        (shared by the single- and multi-exemplar programs)."""
        cfg = self.cfg
        return decode_detections(
            out["objectness"],
            out["regressions"],
            exemplars,
            cls_threshold=cfg.NMS_cls_threshold,
            max_detections=cfg.max_detections,
            box_reg=cfg.box_reg,
            scale_imgsize=cfg.regression_scaling_imgsize,
            scale_wh_only=cfg.regression_scaling_WH_only,
        )

    def _refine_nms(self, dets: dict, feature, image_hw, refiner_params,
                    refine: bool) -> dict:
        """[refine ->] NMS tail (reference test-step order trainer.py:143-150,
        shared by the single- and multi-exemplar programs)."""
        if refine:
            dets = self.refiner.refine(
                refiner_params, feature, dets, image_hw
            )
        return batched_nms(dets, self.cfg.NMS_iou_threshold)

    def _get_fn(self, capacity: int, loss_fn=None,
                chain_feedback: bool = False):
        """Compiled forward -> decode -> [refine] -> NMS program for one
        template-capacity bucket.

        With ``loss_fn(model_out, exemplars, *extra) -> losses`` the program
        additionally returns losses computed from the SAME forward — the
        trainer's eval step (the reference's each_step computes loss and
        Get_pred_boxes from one forward, trainer.py:123-153) — and the
        returned callable takes the extra loss inputs after ``exemplars``.

        ``chain_feedback=True`` is the benchmark hook: the callable takes a
        trailing scalar that is added to the image INSIDE the program and
        returns ``(dets, scalar)``, so chained timing loops execute
        back-to-back on device while measuring this exact production
        program (bench.py / scripts/bench_extra.py).

        There is exactly one copy of this pipeline; every consumer
        (inference, trainer eval, the benchmarks) compiles through it.
        """
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        key = (capacity, refine, loss_fn, chain_feedback)
        if key in self._compiled:
            return self._compiled[key]
        model = self.model.clone(template_capacity=capacity)

        @jax.jit
        def run(params, refiner_params, image, exemplars, *extra):
            if chain_feedback:
                image = image + extra[-1]
                extra = extra[:-1]
            out = model.apply({"params": params}, image, exemplars)
            dets = self._decode(out, exemplars[:, 0, :])
            dets = self._refine_nms(
                dets, out["backbone_feature"],
                (image.shape[1], image.shape[2]), refiner_params, refine,
            )
            fb = jnp.sum(dets["scores"]) * 0.0
            if loss_fn is not None:
                dets = (loss_fn(out, exemplars, *extra), dets)
            if chain_feedback:
                return dets, fb
            return dets

        self._compiled[key] = run
        return run

    def pick_capacity(self, exemplars: np.ndarray, image_size: int) -> int:
        """Host-side template bucket for a batch: the largest per-exemplar need."""
        hw = self.feature_hw(image_size)
        need = 1
        for ex in np.asarray(exemplars).reshape(-1, 4):
            need = max(
                need,
                select_capacity_bucket(ex, hw, hw, self.cfg.template_buckets),
            )
        return need

    def __call__(self, image, exemplars) -> dict:
        """image (B, S, S, 3) float32 normalized; exemplars (B, K, 4).
        Returns dict boxes/scores/refs/valid as fixed-shape device arrays."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        cap = self.pick_capacity(exemplars, int(image.shape[1]))
        fn = self._get_fn(cap)
        return fn(
            self.params,
            self.refiner_params,
            jnp.asarray(image),
            jnp.asarray(exemplars),
        )

    #: static exemplar-count buckets for the multi-exemplar program: the
    #: compiled fn is keyed by bucket, real counts pad up and padded rows'
    #: detections are masked out — variable per-image exemplar counts
    #: (FSCD-LVIS) don't trigger a full recompile each.
    K_BUCKETS = (1, 2, 3, 4, 6, 8)

    def _get_multi_fn(self, capacity: int, k_bucket: int, loss_fn=None):
        """One fused program for K-exemplar inference: encoder ONCE, then the
        matcher/decode pipeline batched over the K exemplars, union NMS.

        The reference runs a full forward per exemplar and one union NMS at
        the end (trainer.py:75-121: per-exemplar Get_pred_boxes with NO
        per-exemplar NMS, concat, [refine], NMS — demo.py:111-132 likewise),
        recomputing the frozen encoder K times. Here the encoder output is
        broadcast to a K-batch for the heads — identical numerics (the
        encoder is deterministic), ~K x fewer encoder FLOPs, one dispatch.

        ``loss_fn(out_k, exemplar_k, *extra) -> losses`` computes one
        exemplar's losses from its B=1 slice of the heads output; the
        program vmaps it over the K axis, masks padded rows, and returns the
        SUM over real exemplars — the reference's multi-exemplar loss
        semantics (trainer.py:102-104,121 sums per-exemplar losses).
        """
        refine = self.refiner is not None and getattr(
            self.cfg, "refine_box", False
        )
        key = ("multi", capacity, k_bucket, refine, loss_fn)
        if key in self._compiled:
            return self._compiled[key]
        model = self.model.clone(template_capacity=capacity)
        heads = model.clone(backbone=_PassthroughBackbone())

        @jax.jit
        def run(params, refiner_params, image, exemplars, k_real, *extra):
            # image (1, S, S, 3); exemplars (k_bucket, 4); k_real () int32
            feat = model.backbone.apply(
                {"params": params["backbone"]}, image
            )
            if isinstance(feat, (list, tuple)):
                if len(feat) != 1:
                    raise NotImplementedError(
                        "fused multi-exemplar inference supports single-"
                        "level backbones only (every shipped backbone is)"
                    )
                feat = feat[0]
            head_params = {n: v for n, v in params.items() if n != "backbone"}
            out = heads.apply(
                {"params": head_params},
                jnp.repeat(feat, k_bucket, axis=0),
                exemplars[:, None, :],
            )
            dets = self._decode(out, exemplars)
            # mask padded exemplar rows, then concat the K per-exemplar slot
            # sets into one image's union
            row_ok = jnp.arange(k_bucket) < k_real
            dets["valid"] = dets["valid"] & row_ok[:, None]
            merged = {
                name: dets[name].reshape((1, -1) + dets[name].shape[2:])
                for name in ("boxes", "scores", "refs", "valid")
            }
            final = self._refine_nms(
                merged, feat, (image.shape[1], image.shape[2]),
                refiner_params, refine,
            )
            if loss_fn is None:
                return final

            def one_exemplar_losses(obj_k, reg_k, ex_k):
                out_k = {
                    "objectness": [o[None] for o in obj_k],
                    # None levels = box regression ablated (matching_net)
                    "regressions": [
                        r[None] if r is not None else None for r in reg_k
                    ],
                }
                return loss_fn(out_k, ex_k[None, None, :], *extra)

            per_k = jax.vmap(one_exemplar_losses)(
                [o for o in out["objectness"]],
                [r for r in out["regressions"]],
                exemplars,
            )
            losses = jax.tree.map(
                lambda v: jnp.where(row_ok, v, 0.0).sum(), per_k
            )
            return losses, final

        self._compiled[key] = run
        return run

    def predict_multi_exemplar(self, image, exemplars, loss_fn=None,
                               loss_args=()):
        """Reference multi-exemplar eval (trainer.py:75-121): per-exemplar
        decode, concatenated, single NMS over the union. image (1, S, S, 3);
        exemplars (K, 4). With ``loss_fn`` (see _get_multi_fn) returns
        (losses summed over exemplars, dets); else just dets."""
        if self.params is None:
            raise RuntimeError("call init_params() or load params first")
        exemplars = np.asarray(exemplars, np.float32).reshape(-1, 4)
        k = len(exemplars)
        k_bucket = next((b for b in self.K_BUCKETS if b >= k), k)
        pad = np.tile(exemplars[-1:], (k_bucket - k, 1))  # masked below
        cap = self.pick_capacity(exemplars, int(image.shape[1]))
        fn = self._get_multi_fn(cap, k_bucket, loss_fn=loss_fn)
        return fn(
            self.params,
            self.refiner_params,
            jnp.asarray(image),
            jnp.asarray(np.concatenate([exemplars, pad], axis=0)),
            jnp.asarray(k, jnp.int32),
            *loss_args,
        )


def detections_to_numpy(dets: dict) -> list:
    """Fixed-slot device detections -> per-image ragged numpy dicts
    (the reference's pred_logits/pred_boxes/ref_points lists)."""
    boxes = np.asarray(dets["boxes"])
    scores = np.asarray(dets["scores"])
    refs = np.asarray(dets["refs"])
    valid = np.asarray(dets["valid"])
    out = []
    for b in range(boxes.shape[0]):
        v = valid[b]
        out.append(
            {"boxes": boxes[b][v], "scores": scores[b][v], "refs": refs[b][v]}
        )
    return out
