"""Training/eval driver (reference trainer.py Matching_Trainer + main.py run
orchestration, re-expressed as an explicit loop over jitted steps).

Covers: per-epoch training, validation every ``AP_term`` epochs
(trainer.py:68-73), the eval step chain forward -> loss -> decode -> NMS ->
per-image JSON logging (:123-153), the epoch-end metrics rendezvous
(:172-206 — process 0 merges, all processes compute, barriers around it),
multi-exemplar eval (:75-121), checkpoint best/last/resume (callbacks.py),
and CSV metric logging (the --nowandb path of main.py:113).
"""

from __future__ import annotations

import csv
import dataclasses
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.data import DataLoader, build_dataset
from tmr_tpu.inference import Predictor, detections_to_numpy
from tmr_tpu.models import build_model
from tmr_tpu.train.state import (
    compute_losses,
    create_train_state,
    make_train_step,
)
from tmr_tpu.utils.checkpoint import CheckpointManager
from tmr_tpu.obs import get_registry, span
from tmr_tpu.utils.profiling import (
    PhaseTimer,
    log_info,
    log_warning,
    step_annotation,
    trace,
)
from tmr_tpu.utils.metrics import (
    coco_style_annotation_generator,
    del_img_log_path,
    get_ap_scores,
    get_mae_rmse,
    image_info_collector,
)


class CSVLogger:
    """Epoch metrics CSV. Rows have varying key sets (val metrics only on
    AP_term epochs), so the file is rewritten with the union of keys —
    never truncating earlier epochs."""

    def __init__(self, logpath: str):
        os.makedirs(logpath, exist_ok=True)
        self.path = os.path.join(logpath, "metrics.csv")
        self._rows: list = []
        if os.path.exists(self.path):  # resume: keep existing history
            with open(self.path, newline="") as f:
                self._rows = list(csv.DictReader(f))

    def log(self, row: Dict[str, float]) -> None:
        self._rows.append({k: str(v) for k, v in row.items()})
        keys = sorted({k for r in self._rows for k in r})
        with open(self.path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for r in self._rows:
                w.writerow(r)


class Trainer:
    """Explicit train/eval driver. Single-process by default; on a mesh the
    jitted steps run sharded (see tmr_tpu.parallel) and the metrics
    rendezvous is gated on jax.process_index() == 0 like the reference's
    rank-0 gating."""

    def __init__(self, cfg, mesh=None):
        self.cfg = cfg
        self.mesh = mesh
        self.model = build_model(cfg, mesh=mesh)
        # --refine_box: build the SAM refiner once and hand it to the
        # Predictor, which runs decode -> refine -> NMS inside the fused
        # program (reference test-step order, trainer.py:143-150)
        refiner = refiner_params = None
        if cfg.refine_box:
            from tmr_tpu.refine import build_refiner

            refiner, refiner_params = build_refiner(cfg, seed=cfg.seed)
        self.predictor = Predictor(
            cfg, model=self.model, refiner=refiner,
            refiner_params=refiner_params,
        )
        self.logger = CSVLogger(cfg.logpath)
        self.wandb = None
        # process-0 gated like every other host-side sink (the reference's
        # WandbLogger is rank-0 only under Lightning DDP)
        if not cfg.nowandb and not cfg.eval and jax.process_index() == 0:
            from tmr_tpu.utils.wandb_logger import WandbLogger

            self.wandb = WandbLogger(
                cfg.project_name, name=os.path.basename(cfg.logpath),
                config=dataclasses.asdict(cfg),
            )
        self.ckpt = CheckpointManager(
            os.path.join(cfg.logpath, "checkpoints"),
            monitor="val/MAE" if cfg.best_model_count else "val/AP",
            mode="min" if cfg.best_model_count else "max",
            every_n_epochs=cfg.AP_term,
            # reference callbacks.py:12-13: a fresh (non-resume, non-eval,
            # single-process) training refuses to clobber an existing logpath
            fresh_guard=not cfg.resume and not cfg.eval
            and jax.process_count() == 1,
        )
        self.state = None
        self._train_step = None
        self._shared_loss_fn = None  # one closure -> one compiled program
        # device-side loss accumulator: one tiny jitted add per step instead
        # of a host float() sync (which would stall the prefetch pipeline)
        self._acc_fn = jax.jit(lambda s, l: jax.tree.map(jnp.add, s, l))
        # weighted variant for eval: batches of different sizes (ragged tail
        # split to B=1 next to full eval_batch_size batches) must contribute
        # per-image, not per-batch, to the epoch mean
        self._scale_fn = jax.jit(
            lambda l, w: jax.tree.map(lambda x: x * w, l)
        )

    # ------------------------------------------------------------ plumbing
    def _loaders(self):
        cfg = self.cfg
        train = DataLoader(
            build_dataset(cfg, "train", eval_mode=False),
            batch_size=cfg.batch_size, shuffle=True, seed=cfg.seed,
            max_gt=cfg.max_gt_boxes, max_exemplars=cfg.num_exemplars,
            num_workers=cfg.num_workers, drop_last=True,
        )
        # reference forces batch_size=1 for val/test (datamodules.py:27,47,50);
        # --eval_batch_size > 1 is the opt-in TPU throughput mode — the
        # loader already groups images by size bucket and the eval step /
        # per-image JSON collector unbatch natively. Multi-exemplar eval
        # stays at 1 (its meta plumbing is per-image).
        eval_bs = cfg.eval_batch_size if cfg.num_exemplars == 1 else 1
        if eval_bs != cfg.eval_batch_size:
            log_warning(
                f"--eval_batch_size {cfg.eval_batch_size} forced to 1: "
                "multi-exemplar eval is per-image (num_exemplars="
                f"{cfg.num_exemplars})"
            )
        val_split = "val" if cfg.dataset == "FSCD147" else "test"
        val = DataLoader(
            build_dataset(cfg, val_split),
            batch_size=eval_bs, shuffle=False, seed=cfg.seed,
            max_gt=cfg.max_gt_boxes, max_exemplars=cfg.num_exemplars,
            num_workers=cfg.num_workers,
        )
        test = DataLoader(
            build_dataset(cfg, "test"),
            batch_size=eval_bs, shuffle=False, seed=cfg.seed,
            max_gt=cfg.max_gt_boxes, max_exemplars=cfg.num_exemplars,
            num_workers=cfg.num_workers,
        )
        return train, val, test

    def _init_state(self, sample_batch, steps_per_epoch: int):
        if self.mesh is not None and "pipe" in self.mesh.shape:
            return self._init_state_pp(sample_batch, steps_per_epoch)
        self.state = create_train_state(
            self.model, self.cfg, jax.random.key(self.cfg.seed),
            jnp.asarray(sample_batch["image"]),
            jnp.asarray(sample_batch["exemplars"]),
            steps_per_epoch=steps_per_epoch,
        )
        step = make_train_step(self.model, self.cfg)
        if self.mesh is not None:
            # DDP replacement: params sharded per the TP rules (replicated on
            # a pure-data mesh), batches split over 'data'; XLA derives the
            # gradient psum from these annotations.
            from tmr_tpu.parallel import shard_params
            from tmr_tpu.parallel.sharding import state_sharding

            self.state = self.state.replace(
                params=shard_params(self.state.params, self.mesh)
            )
            self._train_step = self._jit_step_under_mesh(
                step, state_sharding(self.state, self.mesh)
            )
        else:
            self._train_step = jax.jit(step, donate_argnums=0)

    def _restage_state(self):
        """Re-place a restored state on device exactly as _init_state did.

        CheckpointManager.restore returns HOST numpy leaves by contract
        (the orbax device arrays' sharding annotations pessimize compiled
        programs — the measured 9.2x eval anomaly, utils/checkpoint.py).
        The flip side is that a restore drops the placement _init_state
        established, so resume/test must re-stage: pp stage-major sharding
        on a 'pipe' mesh, the TP/DP state sharding on any other mesh, and
        a plain one-time device_put otherwise (leaving numpy params in
        self.state would instead re-upload the whole tree on every jit
        call)."""
        if self.mesh is not None and "pipe" in self.mesh.shape:
            from tmr_tpu.parallel.pipeline import pp_state_sharding

            self.state = jax.device_put(
                self.state, pp_state_sharding(self.state, self.mesh)
            )
        elif self.mesh is not None:
            from tmr_tpu.parallel.sharding import state_sharding

            self.state = jax.device_put(
                self.state, state_sharding(self.state, self.mesh)
            )
        else:
            self.state = jax.device_put(self.state)

    def _jit_step_under_mesh(self, step, sharding):
        """jit with sharded output state + tracing under set_mesh — NOT a
        bare ``with mesh:``, which mesh-aware ops can't see: the matcher's
        data-axis shard_map island (ops/xcorr.py) discovers the mesh through
        get_abstract_mesh at trace time."""
        jitted = jax.jit(step, out_shardings=(sharding, None),
                         donate_argnums=0)

        def step_under_mesh(state, batch, _jit=jitted, _mesh=self.mesh):
            with jax.sharding.set_mesh(_mesh):
                return _jit(state, batch)

        return step_under_mesh

    def _init_state_pp(self, sample_batch, steps_per_epoch: int):
        """Pipeline-parallel training (--mesh_pipe): stage-sharded params AND
        optimizer moments over 'pipe', GPipe encoder island in the step (the
        reference has nothing comparable — its only training parallelism is
        DDP). Eval/checkpoint interop converts to the dense layout via
        unstack_backbone_params (see eval_epoch)."""
        from tmr_tpu.parallel.pipeline import (
            create_pp_train_state,
            make_pp_train_step,
            pp_state_sharding,
        )

        self.state = create_pp_train_state(
            self.model, self.cfg, jax.random.key(self.cfg.seed),
            jnp.asarray(sample_batch["image"]),
            jnp.asarray(sample_batch["exemplars"]),
            steps_per_epoch=steps_per_epoch,
        )
        sharding = pp_state_sharding(self.state, self.mesh)
        self.state = jax.device_put(self.state, sharding)
        data_axis = "data" if self.mesh.shape.get("data", 1) > 1 else None
        step = make_pp_train_step(
            self.model, self.cfg, self.mesh,
            microbatches=self.cfg.pp_microbatches, data_axis=data_axis,
        )
        self._train_step = self._jit_step_under_mesh(step, sharding)

    def _eval_params(self, params):
        """Params as the dense layout every eval consumer expects — a no-op
        unless training runs pipeline-parallel (stacked 'stages' layout)."""
        if self.mesh is not None and "pipe" in self.mesh.shape:
            from tmr_tpu.parallel.pipeline import unstack_backbone_params

            return unstack_backbone_params(params, self.model.backbone)
        return params

    def _to_device(self, batch: dict) -> dict:
        arrays = {k: v for k, v in batch.items() if k != "meta"}
        if self.mesh is not None:
            from tmr_tpu.parallel.sharding import shard_batch

            return shard_batch(arrays, self.mesh)
        return {k: jnp.asarray(v) for k, v in arrays.items()}

    def _loss_fn(self):
        """Loss closure shared by the fused eval programs:
        (model_out, exemplars (B,K,4), gt_boxes, gt_valid) -> loss dict.
        Built once — the predictor's compile cache is keyed on the closure
        object, so a fresh closure per call would recompile."""
        if self._shared_loss_fn is not None:
            return self._shared_loss_fn
        cfg = self.cfg

        def loss_fn(out, exemplars, gt_boxes, gt_valid):
            return compute_losses(
                out,
                {"exemplars": exemplars, "gt_boxes": gt_boxes,
                 "gt_valid": gt_valid},
                cfg.positive_threshold, cfg.negative_threshold,
                use_focal_loss=cfg.focal_loss,
                scale_imgsize=cfg.regression_scaling_imgsize,
                scale_wh_only=cfg.regression_scaling_WH_only,
            )

        self._shared_loss_fn = loss_fn
        return loss_fn

    def _get_eval_step(self, capacity: int):
        """ONE forward per eval image: losses + decoded/NMS'd detections
        from the same model outputs — the reference's each_step test branch
        (trainer.py:123-153 computes loss and Get_pred_boxes from a single
        forward; running the predictor separately would double the encoder
        cost of every eval epoch). The pipeline itself lives in
        Predictor._get_fn — this only supplies the loss closure."""
        return self.predictor._get_fn(capacity, loss_fn=self._loss_fn())

    # ---------------------------------------------------------------- train
    def fit(self, max_steps_per_epoch: Optional[int] = None) -> None:
        cfg = self.cfg
        train, val, _ = self._loaders()
        steps = len(train) if max_steps_per_epoch is None else min(
            len(train), max_steps_per_epoch
        )

        start_epoch = 0
        it0 = iter(train)
        try:
            first = next(it0)
        finally:
            it0.close()  # don't leave the prefetch pool suspended
        self._init_state(first, steps)
        if cfg.resume and self.ckpt.last_path():
            self.state = self.ckpt.restore(self.ckpt.last_path(), self.state)
            self._restage_state()
            start_epoch = self.ckpt.meta["last_epoch"] + 1
            log_info(f"resumed from epoch {start_epoch}")

        for epoch in range(start_epoch, cfg.max_epochs):
            train.set_epoch(epoch)
            t0 = time.time()
            sums = None  # device-scalar pytree, fetched once per epoch
            n = 0
            # per-epoch timer; phases also open obs spans ("train.data" /
            # "train.step" / "train.metrics") when TMR_TRACE=1 so the step
            # loop lands on the same trace as serve/map
            timers = PhaseTimer(span_prefix="train.")
            # capture an xprof trace of the first post-resume epoch
            profile = cfg.profile_dir if epoch == start_epoch else None
            with trace(profile):
                it = iter(train)
                try:
                    # one-batch device prefetch: the NEXT batch's host decode
                    # + H2D transfer run while the CURRENT step computes on
                    # device (jit dispatch is async; the loss float() below
                    # is the only sync point)
                    with timers.phase("data"):
                        nxt = next(it, None)
                        nxt = self._to_device(nxt) if nxt is not None else None
                    for i in range(steps):
                        if nxt is None:
                            break
                        batch = nxt
                        with timers.phase("step"), step_annotation("train", i):
                            self.state, losses = self._train_step(
                                self.state, batch
                            )
                        with timers.phase("data"):
                            # no dead fetch past the epoch's last step
                            nxt = next(it, None) if i + 1 < steps else None
                            nxt = (
                                self._to_device(nxt)
                                if nxt is not None else None
                            )
                        with timers.phase("metrics"):
                            # accumulate ON DEVICE: the step loop has no host
                            # sync point, so compute overlaps the next batch's
                            # decode + H2D end to end (VERDICT r2 #7)
                            sums = (
                                losses if sums is None
                                else self._acc_fn(sums, losses)
                            )
                        n += 1
                finally:
                    # release the loader's worker pool + prefetch window now,
                    # not whenever the suspended generator gets GC'd
                    it.close()
            # single per-epoch device fetch of the loss sums
            sums_host = (
                {} if sums is None
                else {k: float(v) for k, v in jax.device_get(sums).items()}
            )
            row = {f"train/{k}": v / max(n, 1) for k, v in sums_host.items()}
            row["epoch"] = epoch
            row["train/sec"] = time.time() - t0
            row.update(timers.as_dict())
            # fold the epoch's phase distributions into the process-wide
            # registry (train/time/<phase> histograms) — once per timer,
            # so epochs accumulate without double-counting
            timers.to_registry(get_registry(), prefix="train/time/")

            ap_epoch = epoch == 0 or (epoch % cfg.AP_term == cfg.AP_term - 1)
            if ap_epoch:
                row.update(self.eval_epoch(val, "val", self.state.params))
            self.logger.log(row)
            if self.wandb is not None:
                self.wandb.log(row, step=epoch)
            line = f"Epoch {epoch}: | " + " | ".join(
                f"{k}: {v:.4f}" for k, v in sorted(row.items()) if k != "epoch"
            )
            # stderr protocol line: stdout stays reserved for machine-
            # readable report output (the stdout-hygiene tier-1 lint)
            log_info(line)
            self.ckpt.save_epoch(self.state, epoch, row)
        self.ckpt.wait()
        if self.wandb is not None:
            self.wandb.finish()

    # ----------------------------------------------------------------- eval
    @staticmethod
    def _split_per_image(batch: dict):
        """Ragged tail batch -> B=1 sub-batches. Each size bucket's leftover
        has its own batch dim; compiling the whole eval program once per
        leftover shape would cost a full XLA compile for a batch used once
        per epoch — B=1 is one stable extra shape instead."""
        b = batch["image"].shape[0]
        for i in range(b):
            yield {
                k: (v[i : i + 1] if k != "meta" else [v[i]])
                for k, v in batch.items()
            }

    def eval_epoch(self, loader, stage: str, params) -> Dict[str, float]:
        cfg = self.cfg
        self.predictor.params = self._eval_params(params)
        sums = None  # device-scalar pytree, fetched once per epoch
        n = 0
        # one-batch software pipeline: batch k's detections are fetched only
        # AFTER batch k+1's H2D upload and compute have been dispatched
        # (both async), so the host->device transfer — the dominant cost on
        # slow links — overlaps the previous batch's compute instead of
        # serializing with its result fetch
        # (bsz, meta, losses, dets) awaiting collection — only size + meta
        # from the host batch, so `pending` itself doesn't pin batch k's
        # image/gt arrays across the overlap (loop locals still hold the
        # current batch, so peak residency is the loader's usual window)
        pending = None

        def collect(p):
            nonlocal sums, n
            bsz, meta, losses, dets = p
            # weight each batch's losses by its size so a ragged-tail B=1
            # image doesn't weigh as much as a full batch. NB this is
            # batch-size weighting, not exact per-image parity: the
            # criterion normalizes by the batch's TOTAL positive count
            # (criterion.py), so batched losses still differ from the
            # eval_batch_size=1 aggregation — the documented caveat on
            # --eval_batch_size. Still device-side, no host sync.
            scaled = self._scale_fn(losses, jnp.float32(bsz))
            sums = scaled if sums is None else self._acc_fn(sums, scaled)
            n += bsz
            image_info_collector(
                cfg.logpath, stage, meta, detections_to_numpy(dets)
            )

        for full_batch in loader:
            b = full_batch["image"].shape[0]
            if cfg.num_exemplars == 1 and b not in (1, cfg.eval_batch_size):
                sub_batches = self._split_per_image(full_batch)
            else:
                sub_batches = [full_batch]
            for batch in sub_batches:
                with span("eval.batch", stage=stage):
                    losses, dets = self._eval_batch(batch)  # async dispatch
                if pending is not None:
                    collect(pending)
                pending = (
                    int(batch["image"].shape[0]), batch["meta"], losses, dets
                )
        if pending is not None:
            collect(pending)
        return self._finish_eval(stage, sums, n)

    def _eval_batch(self, batch: dict):
        cfg = self.cfg
        params = self.predictor.params
        if cfg.num_exemplars > 1:
            # one fused program: per-exemplar losses SUMMED (reference
            # trainer.py:102-104,121) + union detections
            losses, dets = self.predictor.predict_multi_exemplar(
                batch["image"], batch["meta"][0]["orig_exemplars"]
                / np.array(batch["meta"][0]["img_size"].tolist() * 2,
                           np.float32),
                loss_fn=self._loss_fn(),
                loss_args=(jnp.asarray(batch["gt_boxes"]),
                           jnp.asarray(batch["gt_valid"])),
            )
        else:
            # fused: losses + detections from one forward
            cap = self.predictor.pick_capacity(
                batch["exemplars"], int(batch["image"].shape[1])
            )
            fn = self._get_eval_step(cap)
            keys = ("image", "exemplars", "gt_boxes", "gt_valid")
            mesh = self.mesh
            if (
                mesh is not None
                and mesh.shape.get("data", 1) > 1
                and batch["image"].shape[0] % mesh.shape["data"] == 0
            ):
                # data-sharded eval: with --eval_batch_size a multiple of
                # the 'data' axis, the fused eval program runs each image
                # shard on its own devices (the reference's DDP eval
                # spreads ranks the same way; per-image JSON collection
                # and the rank-0 merge are already shard-order agnostic).
                # shard_batch device_puts host arrays straight to their
                # sharding — one transfer, same helper _to_device uses.
                from tmr_tpu.parallel.sharding import shard_batch

                sharded = shard_batch({k: batch[k] for k in keys}, mesh)
                with jax.sharding.set_mesh(mesh):
                    losses, dets = fn(
                        params, self.predictor.refiner_params,
                        *(sharded[k] for k in keys),
                    )
            else:
                losses, dets = fn(
                    params, self.predictor.refiner_params,
                    *(jnp.asarray(batch[k]) for k in keys),
                )
        return losses, dets

    def _finish_eval(self, stage: str, sums, n: int) -> Dict[str, float]:
        cfg = self.cfg
        sums_host = (
            {} if sums is None
            else {k: float(v) for k, v in jax.device_get(sums).items()}
        )
        metrics = {f"{stage}/{k}": v / max(n, 1) for k, v in sums_host.items()}

        # epoch-end rendezvous (trainer.py:181-199): process 0 merges the
        # per-image JSONs; every process computes the metrics from the files.
        if jax.process_count() > 1:  # pragma: no cover - multihost only
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tmr_eval_pre_merge")
        if jax.process_index() == 0:
            coco_style_annotation_generator(cfg.logpath, stage)
        if jax.process_count() > 1:  # pragma: no cover
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tmr_eval_post_merge")

        mae, rmse = get_mae_rmse(cfg.logpath, stage)
        ap, ap50, ap75 = get_ap_scores(cfg.logpath, stage)
        metrics.update(
            {f"{stage}/AP": ap, f"{stage}/AP50": ap50, f"{stage}/AP75": ap75,
             f"{stage}/MAE": mae, f"{stage}/RMSE": rmse}
        )
        if jax.process_index() == 0:
            log_info(
                f"{stage}/AP: {ap:.2f} | {stage}/AP50: {ap50:.2f} | "
                f"{stage}/AP75: {ap75:.2f} | {stage}/MAE: {mae:.2f} | "
                f"{stage}/RMSE: {rmse:.2f}"
            )
            if cfg.visualize:
                # triptychs + PR curves (log_utils.py:311-377, 447-491);
                # best-effort: visualization must never fail an eval run
                from tmr_tpu.utils.profiling import log_warning
                from tmr_tpu.utils.visualize import (
                    plot_pr_curves,
                    save_triptychs,
                )

                try:
                    save_triptychs(cfg.logpath, stage)
                    plot_pr_curves(cfg.logpath, stage)
                except Exception as e:  # pragma: no cover
                    log_warning(f"visualization failed: {e}")
            del_img_log_path(cfg.logpath, stage)
        return metrics

    def test(self, params=None) -> Dict[str, float]:
        """Eval-mode entry (reference main.py:122-130): load the best
        checkpoint unless params are given, run the test loop."""
        _, _, test = self._loaders()
        if params is None:
            if self.state is None:
                first = next(iter(test))
                self._init_state(first, steps_per_epoch=1)
            best = self.ckpt.best_path()
            if best is None:
                # mirror the reference, which fails when no checkpoint
                # resolves for --eval (callbacks.py:40-45 / main.py:124-129)
                raise FileNotFoundError(
                    f"--eval: no best_model checkpoint under "
                    f"{self.ckpt.directory}; train first or pass params"
                )
            self.state = self.ckpt.restore(best, self.state)
            self._restage_state()
            params = self.state.params
        return self.eval_epoch(test, "test", params)
