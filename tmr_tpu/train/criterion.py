"""Loss computation (reference criterion/criterions_TM.py:7-58).

The reference gathers positive/negative locations into ragged 1-D tensors
then sums; here the identical sums are computed as masked reductions over
the full maps, so the loss is shape-static and jit-fused with the forward.

Normalization semantics preserved exactly (SetCriterion_TM.forward :40-52):
- BCE (or focal) summed over positive+negative locations, / num_positive;
- gIoU summed over positive locations, / num_positive;
- num_positive counts ALL positive locations in the batch, PLUS one per
  image with zero positives — those images contribute a degenerate-box dummy
  whose gIoU loss is exactly 1.0 (TM_utils.py:201-203 with eps 1e-13);
- losses averaged over levels.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from tmr_tpu.ops.boxes import (
    cxcywh_to_xyxy,
    decode_regression,
    generalized_box_iou_loss,
)


def bce_with_logits(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Elementwise binary cross-entropy on logits (stable form)."""
    return jnp.maximum(logits, 0.0) - logits * targets + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def focal_loss_elementwise(
    logits: jnp.ndarray, targets: jnp.ndarray, alpha: float = 0.25, gamma: float = 2.0
) -> jnp.ndarray:
    """WeightedFocalLoss (criterions_TM.py:15-29): at*(1-pt)^g * BCE with
    at = alpha for target 1, (1-alpha) for target 0."""
    bce = bce_with_logits(logits, targets)
    at = jnp.where(targets > 0.5, alpha, 1.0 - alpha)
    pt = jnp.exp(-bce)
    return at * (1.0 - pt) ** gamma * bce


def criterion(
    objectness: Sequence[jnp.ndarray],  # per level (B, H, W) logits
    regressions: Sequence[jnp.ndarray],  # per level (B, H, W, 4) or None
    targets: Sequence[dict],  # per level assign_targets output
    exemplars: jnp.ndarray,  # (B, 4)
    use_focal_loss: bool = False,
    scale_imgsize: bool = False,
    scale_wh_only: bool = False,
) -> dict:
    ce_losses, giou_losses = [], []
    for level, (obj, reg, tgt) in enumerate(zip(objectness, regressions, targets)):
        pos = tgt["positive"].astype(jnp.float32)  # (B, H, W)
        neg = tgt["negative"].astype(jnp.float32)

        elem = focal_loss_elementwise if use_focal_loss else bce_with_logits
        ce_map = elem(obj, jnp.ones_like(obj)) * pos + elem(
            obj, jnp.zeros_like(obj)
        ) * neg
        ce_sum = ce_map.sum()

        if reg is None:
            # ablation_no_box_regression: zero deltas -> exemplar-size boxes
            reg = jnp.zeros(obj.shape + (4,), jnp.float32)
        pred_xywh = decode_regression(reg, exemplars, scale_imgsize, scale_wh_only)
        giou_map = generalized_box_iou_loss(
            cxcywh_to_xyxy(pred_xywh), cxcywh_to_xyxy(tgt["box_target"])
        )  # (B, H, W)
        giou_sum = (giou_map * pos).sum()

        pos_per_img = pos.sum(axis=(1, 2))  # (B,)
        empty = (pos_per_img == 0).astype(jnp.float32)
        num_positive = pos_per_img.sum() + empty.sum()
        # zero-positive images contribute the degenerate-dummy loss of 1.0
        giou_sum = giou_sum + empty.sum()

        ce_losses.append(ce_sum / num_positive)
        giou_losses.append(giou_sum / num_positive)

    loss_ce = jnp.stack(ce_losses).mean()
    loss_giou = jnp.stack(giou_losses).mean()
    return {"loss_ce": loss_ce, "loss_giou": loss_giou,
            "loss": loss_ce + loss_giou}
