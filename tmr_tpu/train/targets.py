"""Vectorized target assignment (reference utils/TM_utils.py GT_map, :20-222).

The reference loops Python-side over levels x batch x GT boxes, building
per-location positive/negative/ignore maps. Here the whole assignment is one
batched masked computation over a (locations, boxes) grid — vmap over batch,
broadcast over boxes — so it lives inside the jitted train step. Variable GT
counts become a padded (B, M, 4) array + validity mask.

Semantics preserved exactly:
- location grid at *corner* coordinates (get_template is_center=False,
  TM_utils.py:124);
- nearest-center one-hot per box by L1 distance, first-min tie-break
  (Get_is_center :56-67);
- diamond in/out tests with ratio -h/w and threshold-derived biases
  (Get_is_in_out_positive :77-92), with the threshold==1.0 overrides (:146-147);
- exemplar-sized boundary exclusion with odd-ified span (Get_not_in_boundary
  :36-54);
- is_center folded into positives only on the last level (:152-155);
- boundary-excluded positives demoted to negatives (:157-158);
- smallest-area box wins contested locations (:161-165);
- ignore = (some box doesn't claim positive) & (some box doesn't claim
  negative) & in-boundary, negatives are the complement (:168-170).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def location_centers(h: int, w: int) -> jnp.ndarray:
    """(L, 2) [x, y] normalized corner coordinates, row-major like
    get_template(..., is_center=False) (TM_utils.py:26-34,124)."""
    xs = jnp.arange(w, dtype=jnp.float32) / w
    ys = jnp.arange(h, dtype=jnp.float32) / h
    gx, gy = jnp.meshgrid(xs, ys)  # default 'xy': gx/gy are (h, w)
    return jnp.stack([gx.reshape(-1), gy.reshape(-1)], axis=1)


def boundary_mask(exemplar: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """(L,) bool interior mask (Get_not_in_boundary, TM_utils.py:36-54)."""
    x1 = jnp.clip(exemplar[0], 0.0, 1.0) * w
    y1 = jnp.clip(exemplar[1], 0.0, 1.0) * h
    x2 = jnp.clip(exemplar[2], 0.0, 1.0) * w
    y2 = jnp.clip(exemplar[3], 0.0, 1.0) * h
    xi1 = jnp.floor(x1).astype(jnp.int32)
    xi2 = jnp.ceil(x2).astype(jnp.int32)
    yi1 = jnp.floor(y1).astype(jnp.int32)
    yi2 = jnp.ceil(y2).astype(jnp.int32)
    wspan = xi2 - xi1
    hspan = yi2 - yi1
    xi2 = xi2 - (wspan % 2 == 0)
    yi2 = yi2 - (hspan % 2 == 0)
    pad_x = (xi2 - xi1) // 2
    pad_y = (yi2 - yi1) // 2
    ys = jnp.arange(h)
    xs = jnp.arange(w)
    row = (ys >= pad_y) & (ys < h - pad_y)
    col = (xs >= pad_x) & (xs < w - pad_x)
    return (row[:, None] & col[None, :]).reshape(-1)


def _assign_one(
    gt_boxes: jnp.ndarray,  # (M, 4) xyxy normalized, padded
    gt_valid: jnp.ndarray,  # (M,) bool
    exemplar: jnp.ndarray,  # (4,)
    h: int,
    w: int,
    positive_threshold: float,
    negative_threshold: float,
    is_last_level: bool,
):
    L = h * w
    centers = location_centers(h, w)  # (L, 2)
    cxs, cys = centers[:, 0], centers[:, 1]

    x1, y1, x2, y2 = gt_boxes[:, 0], gt_boxes[:, 1], gt_boxes[:, 2], gt_boxes[:, 3]
    cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
    bw, bh = x2 - x1, y2 - y1

    rel_x = jnp.abs(cxs[:, None] - cx[None, :])  # (L, M)
    rel_y = jnp.abs(cys[:, None] - cy[None, :])

    # nearest-center one-hot per box (first-min tie-break like torch.argmin)
    center_idx = jnp.argmin(rel_x + rel_y, axis=0)  # (M,)
    is_center = jax.nn.one_hot(center_idx, L, dtype=jnp.bool_).T  # (L, M)

    ratio = -bh / bw
    bias_p = ((1 - positive_threshold) / (1 + positive_threshold)) * bh
    bias_n = ((1 - negative_threshold) / (1 + negative_threshold)) * bh
    is_in_positive = ratio[None, :] * rel_x + bias_p[None, :] >= rel_y
    is_in_negative = ratio[None, :] * rel_x + bias_n[None, :] < rel_y

    if positive_threshold == 1.0:
        is_in_positive = is_center
    if negative_threshold == 1.0:
        is_in_negative = ~is_center

    in_bounds = boundary_mask(exemplar, h, w)[:, None]  # (L, 1)

    if is_last_level:
        pos_cand = is_center | is_in_positive
    else:
        pos_cand = is_in_positive
    is_in_negative = is_in_negative | (pos_cand & ~in_bounds)
    pos_cand = pos_cand & in_bounds

    valid = gt_valid[None, :]
    # smallest-area box claims each contested location
    area = bw * bh
    area_grid = jnp.where(pos_cand & valid, area[None, :], 1e8)
    box_id = jnp.argmin(area_grid, axis=1)  # (L,)
    cxcywh = jnp.stack([cx, cy, bw, bh], axis=1)  # (M, 4)
    box_target = cxcywh[box_id]  # (L, 4)

    positive = (pos_cand & valid).any(axis=1)
    ignore = (
        (~pos_cand & valid).any(axis=1)
        & (~is_in_negative & valid).any(axis=1)
        & in_bounds[:, 0]
    )
    negative = ~(positive | ignore)

    return {
        "positive": positive.reshape(h, w),
        "negative": negative.reshape(h, w),
        "box_target": box_target.reshape(h, w, 4),
    }


def assign_targets(
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    exemplars: jnp.ndarray,
    h: int,
    w: int,
    positive_threshold: float,
    negative_threshold: float,
    is_last_level: bool = True,
):
    """Batched GT assignment.

    gt_boxes (B, M, 4) normalized xyxy (padded), gt_valid (B, M) bool,
    exemplars (B, 4). Returns dict of positive/negative (B, h, w) bool and
    box_target (B, h, w, 4) cxcywh.
    """
    return jax.vmap(
        lambda b, v, e: _assign_one(
            b, v, e, h, w, positive_threshold, negative_threshold, is_last_level
        )
    )(gt_boxes, gt_valid, exemplars)
