"""Training layer: target assignment, losses, optimizer/train state."""

from tmr_tpu.train.targets import assign_targets  # noqa: F401
from tmr_tpu.train.criterion import criterion, focal_loss_elementwise  # noqa: F401
from tmr_tpu.ops.boxes import decode_regression  # noqa: F401  (re-export)
from tmr_tpu.train.state import (  # noqa: F401
    TrainState,
    create_train_state,
    make_optimizer,
    make_train_step,
)
