"""Optimizer + train step (reference trainer.py:208-236 + Lightning wiring).

Reference recipe: AdamW with two LR groups — backbone params at
``lr_backbone`` (0 in every published script => frozen), everything else at
``lr`` — weight decay 1e-4, global-norm grad clip 0.1 (main.py:116), and
MultiStepLR x0.1 at 60% of training when ``lr_drop`` (trainer.py:227-234).

TPU-native expression: one optax chain — clip_by_global_norm ->
multi_transform{head: adamw(sched), backbone: adamw(sched)|set_to_zero}.
``set_to_zero`` for frozen groups means frozen params carry no optimizer
state (no m/v buffers), saving HBM for the 632M-param ViT-H. FrozenBatchNorm
statistics are always in the frozen group regardless of backbone LR.

The train step is a pure jittable function; data parallelism comes from
sharding its inputs over a mesh (see tmr_tpu/parallel), not from a wrapper.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import traverse_util
from flax.training import train_state

from tmr_tpu.train.criterion import criterion
from tmr_tpu.train.targets import assign_targets


class TrainState(train_state.TrainState):
    pass


def param_labels(params: Any, frozen_backbone: bool) -> Any:
    """Label tree for multi_transform: 'head' | 'backbone' | 'frozen'.

    - everything under the top-level 'backbone' module is the backbone group
      (the reference matches parameter names on the substring 'backbone',
      trainer.py:210-225);
    - FrozenBatchNorm running statistics are always 'frozen';
    - frozen_backbone switches the whole backbone group to 'frozen'.
    """
    flat = traverse_util.flatten_dict(params)
    labels = {}
    for path in flat:
        if any(k in ("running_mean", "running_var") for k in path):
            labels[path] = "frozen"
        elif path[0] == "backbone":
            labels[path] = "frozen" if frozen_backbone else "backbone"
        else:
            labels[path] = "head"
    return traverse_util.unflatten_dict(labels)


def make_optimizer(cfg, steps_per_epoch: int) -> optax.GradientTransformation:
    accum = cfg.grad_accum_steps
    # the piecewise schedule advances once per OPTIMIZER UPDATE — under
    # MultiSteps that is once per k micro-steps, so the 60% milestone must
    # be expressed in updates, not in data steps
    updates_per_epoch = max(steps_per_epoch // max(accum, 1), 1)
    if cfg.lr_drop:
        milestone = int(cfg.max_epochs * 0.6) * updates_per_epoch
    else:
        milestone = (cfg.max_epochs + 1) * updates_per_epoch

    def sched(base):
        return optax.piecewise_constant_schedule(base, {milestone: 0.1})

    frozen_backbone = cfg.lr_backbone == 0 or cfg.backbone.endswith("_FRZ")
    transforms = {
        "head": optax.adamw(sched(cfg.lr), weight_decay=cfg.weight_decay),
        "backbone": optax.adamw(sched(cfg.lr_backbone),
                                weight_decay=cfg.weight_decay),
        "frozen": optax.set_to_zero(),
    }
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.clip_max_norm),
        optax.multi_transform(
            transforms, lambda p: param_labels(p, frozen_backbone)
        ),
    )
    if accum > 1:
        # mean-accumulate k micro-step gradients, apply ONE update every k
        # steps (params are bit-identical in between) — one chip reaches the
        # reference's DDP effective batch without the memory of a big batch
        tx = optax.MultiSteps(tx, every_k_schedule=accum)
    return tx


def create_train_state(
    model, cfg, rng, sample_image, sample_exemplars, steps_per_epoch: int = 1000
) -> TrainState:
    # jitted init — eager init is op-by-op (slow on remote/tunneled devices)
    params = jax.jit(model.init)(rng, sample_image, sample_exemplars)["params"]
    tx = make_optimizer(cfg, steps_per_epoch)
    return TrainState.create(
        apply_fn=model.apply, params=params, tx=tx
    )


def compute_losses(
    model_out: dict,
    batch: dict,
    positive_threshold: float,
    negative_threshold: float,
    use_focal_loss: bool = False,
    scale_imgsize: bool = False,
    scale_wh_only: bool = False,
) -> dict:
    """Forward outputs + batch -> loss dict (the body of trainer.py:132-137).

    batch: image (B,S,S,3), exemplars (B,K,4), gt_boxes (B,M,4) normalized
    xyxy padded, gt_valid (B,M) bool.
    """
    ex0 = batch["exemplars"][:, 0, :]
    num_levels = len(model_out["objectness"])
    targets = []
    for lvl, obj in enumerate(model_out["objectness"]):
        h, w = obj.shape[1], obj.shape[2]
        targets.append(
            assign_targets(
                batch["gt_boxes"],
                batch["gt_valid"],
                ex0,
                h,
                w,
                positive_threshold,
                negative_threshold,
                is_last_level=(lvl == num_levels - 1),
            )
        )
    return criterion(
        model_out["objectness"],
        model_out["regressions"],
        targets,
        ex0,
        use_focal_loss=use_focal_loss,
        scale_imgsize=scale_imgsize,
        scale_wh_only=scale_wh_only,
    )


def make_train_step(model, cfg, forward_fn: Callable = None) -> Callable:
    """Build the jittable train step. Static config is closed over; the
    returned fn is (state, batch) -> (state, metrics) and is safe to wrap in
    jax.jit with sharded inputs.

    ``forward_fn(params, image, exemplars) -> model_out`` overrides the
    default ``model.apply`` forward — the pipeline-parallel step
    (parallel/pipeline.make_pp_train_step) routes the encoder through its
    GPipe island this way while sharing all the loss/containment logic."""

    if forward_fn is None:
        def forward_fn(params, image, exemplars):
            return model.apply({"params": params}, image, exemplars)

    def train_step(state: TrainState, batch: dict):
        def loss_fn(params):
            out = forward_fn(params, batch["image"], batch["exemplars"])
            losses = compute_losses(
                out,
                batch,
                cfg.positive_threshold,
                cfg.negative_threshold,
                use_focal_loss=cfg.focal_loss,
                scale_imgsize=cfg.regression_scaling_imgsize,
                scale_wh_only=cfg.regression_scaling_WH_only,
            )
            return losses["loss"], losses

        (loss, losses), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # failure containment the reference lacks (SURVEY §5.3: "training
        # side: none"): a non-finite loss OR any non-finite gradient leaf
        # (backward-only overflow) discards the whole step — params,
        # optimizer moments, and the schedule step all keep their previous
        # values — while the loss dict still reports the event.
        finite_leaves = [
            jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)
        ]
        ok = jnp.isfinite(loss) & jnp.all(jnp.stack(finite_leaves))
        new_state = state.apply_gradients(grads=grads)
        state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old), new_state, state
        )
        losses["skipped_nonfinite"] = (~ok).astype(jnp.float32)
        return state, losses

    return train_step

