"""Shared diagnostic warning types + the structured gate-refusal registry
(dependency-free at import time — importable from any layer: ops, models,
utils).

``FormulationFallbackWarning`` is the structural contract between the
trace-time formulation dispatchers (models/vit.py attention, ops/xcorr.py
correlation) and the measurement harnesses (utils/autotune.py sweeps,
scripts/profile_breakdown.py): when an EXPLICITLY requested formulation is
refused by its gate/dtype precondition and a fallback traces instead, the
dispatcher warns with this category carrying ``env_var`` — so harnesses can
detect by category + attribute (not message substrings) that a timing
recorded under the requested label actually measured the fallback.

The gate-refusal REGISTRY is the machine-readable side of the same story
(round-5 verdict #1: on the live TPU every require_tpu kernel fell back
and the gates swallowed WHY). Every refusal inside the compiled
self-checks (ops/flash_attn._self_check and the gates built on it —
pallas_global_ok, pallas_fused_ok, pallas_window_ok, flash_attention_ok,
…) records a ``gate_probe.json``-schema cause here: refusal category,
exception class + message when one was swallowed, the tile/geometry
config the verdict keys on, and the device kind. Consumers drain it:
scripts/gate_probe.py --json emits the causes next to each probe, and the
autotune sweeps attach them to fallback-labeled rows so a "(fallback)"
timing always travels with the reason the requested kernel refused.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: schema tag stamped on every refusal record and on the gate_probe.py
#: --json document — bump when the record shape changes incompatibly
GATE_PROBE_SCHEMA = "gate_probe/v1"

#: schema tag of the structured map-phase extraction report
#: (parallel/mapreduce.py MapReport, emitted by `map --report_out`) — the
#: gate_probe/v1 pattern applied to fault tolerance: per-shard
#: status/attempts/causes, quarantine and resume lists, skipped-image and
#: non-finite counts, retry totals, wall-clock per shard. bench/CI assert
#: on it via ``validate_map_report`` (scripts/chaos_probe.py).
MAP_REPORT_SCHEMA = "map_report/v1"

#: closed per-shard status vocabulary in a map_report/v1 document
MAP_SHARD_STATUSES = ("ok", "quarantined", "resumed")

#: closed per-attempt failure-cause vocabulary ("timeout" = the per-shard
#: wall-clock budget elapsed; "exception" carries class + message)
MAP_FAILURE_CAUSES = ("timeout", "exception")


#: schema tag of a metrics-registry snapshot (tmr_tpu/obs/metrics.py
#: ``MetricsRegistry.snapshot()``): every named counter/gauge/histogram at
#: one instant. Report emitters attach it under a ``metrics`` key so one
#: JSON line carries latency AND counter state; ``validate_map_report`` /
#: ``validate_serve_report`` validate the attachment when present.
METRICS_REPORT_SCHEMA = "metrics_report/v1"


def validate_metrics_report(doc: dict) -> List[str]:
    """Structural check of a metrics_report/v1 document; returns a list
    of problems (empty == valid). Dependency-free like the others."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != METRICS_REPORT_SCHEMA:
        problems.append(
            f"schema != {METRICS_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    for section in ("counters", "gauges", "histograms"):
        if not isinstance(doc.get(section), dict):
            problems.append(f"{section}: not a dict")
    for name, v in (doc.get("counters") or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"counters[{name!r}]: not a number")
    for name, v in (doc.get("gauges") or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"gauges[{name!r}]: not a number")
    for name, h in (doc.get("histograms") or {}).items():
        where = f"histograms[{name!r}]"
        if not isinstance(h, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("buckets_le", "counts", "count", "sum",
                    "p50", "p95", "p99"):
            if key not in h:
                problems.append(f"{where}: missing {key!r}")
        bounds, counts = h.get("buckets_le"), h.get("counts")
        if isinstance(bounds, list) and isinstance(counts, list) \
                and len(counts) != len(bounds) + 1:
            problems.append(
                f"{where}: counts must have len(buckets_le)+1 entries "
                "(overflow bucket)"
            )
    return problems


def _validate_metrics_attachment(doc: dict) -> List[str]:
    """Shared rule for report documents carrying an optional ``metrics``
    key: when present it must be a valid metrics_report/v1."""
    if "metrics" not in doc:
        return []
    return [f"metrics: {p}" for p in validate_metrics_report(doc["metrics"])]


def _validate_mfu_attachment(doc: dict) -> List[str]:
    """Shared rule for report documents carrying an optional ``mfu`` key
    (serve_report/map_report when the flight recorder is on): when
    present it must be a valid mfu_report/v1."""
    if "mfu" not in doc:
        return []
    return [f"mfu: {p}" for p in validate_mfu_report(doc["mfu"])]


#: schema tag of the per-program device-time / MFU accounting document
#: (tmr_tpu/obs/devtime.py ``mfu_report()``): for every executed
#: ``Predictor._compiled`` program — achieved FLOP/s from attributed
#: device seconds, MFU against the platform peak, and a compute- vs
#: memory-bound roofline classification from the program's arithmetic
#: intensity. Attached to serve_report/map_report under ``mfu`` when
#: ``TMR_FLIGHT=1``; scripts/obs_watch.py is the measured proof.
MFU_REPORT_SCHEMA = "mfu_report/v1"

#: closed roofline-classification vocabulary in an mfu_report/v1
#: program record ("unknown" = no bytes-accessed figure was available,
#: so the intensity could not be placed against the ridge)
ROOFLINE_BOUNDS = ("compute", "memory", "unknown")

#: closed cost-model provenance vocabulary: "xla" = the compiled
#: program's own ``cost_analysis()``, "analytic" = the
#: devtime.forward_tflops_per_image model, "none" = neither applied
MFU_COST_SOURCES = ("xla", "analytic", "none")


def validate_mfu_report(doc: dict) -> List[str]:
    """Structural check of an mfu_report/v1 document; returns a list of
    problems (empty == valid). Dependency-free like the others."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != MFU_REPORT_SCHEMA:
        problems.append(
            f"schema != {MFU_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    plat = doc.get("platform")
    if not isinstance(plat, dict):
        problems.append("platform: not a dict")
    else:
        for key in ("backend", "device_kind", "peak_tflops", "peak_gbps",
                    "peak_source"):
            if key not in plat:
                problems.append(f"platform: missing {key!r}")
        pk = plat.get("peak_tflops")
        if not isinstance(pk, (int, float)) or isinstance(pk, bool) \
                or pk <= 0:
            problems.append("platform.peak_tflops: not a positive number")
    programs = doc.get("programs")
    if not isinstance(programs, list):
        problems.append("programs: not a list")
        programs = []
    for i, p in enumerate(programs):
        where = f"programs[{i}]"
        if not isinstance(p, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("kind", "key", "bucket", "calls", "warmup_calls",
                    "dispatch_s", "device_s", "wall_s", "cost_source",
                    "mfu", "bound"):
            if key not in p:
                problems.append(f"{where}: missing {key!r}")
        if p.get("bound") not in ROOFLINE_BOUNDS:
            problems.append(f"{where}: bad bound {p.get('bound')!r}")
        if p.get("cost_source") not in MFU_COST_SOURCES:
            problems.append(
                f"{where}: bad cost_source {p.get('cost_source')!r}"
            )
        mfu = p.get("mfu")
        if mfu is not None and (
            not isinstance(mfu, (int, float)) or isinstance(mfu, bool)
        ):
            problems.append(f"{where}.mfu: not a number or null")
    totals = doc.get("totals")
    if not isinstance(totals, dict):
        problems.append("totals: not a dict")
    else:
        for key in ("device_s", "flops", "achieved_tflops", "mfu"):
            if key not in totals:
                problems.append(f"totals: missing {key!r}")
    return problems


#: closed anomaly vocabulary the flight recorder's health watch can emit
#: (tmr_tpu/obs/flight.py HealthWatch): recompile_storm = key-change
#: compile events over threshold in one window; latency_regression =
#: window p99 beyond factor x rolling baseline; queue_saturation =
#: batcher depth over threshold; cache_hit_collapse = window hit rate
#: collapsed vs rolling baseline; mfu_drop = window achieved FLOP/s
#: below factor x rolling baseline. The fleet kinds (FLEET_ANOMALY_KINDS,
#: tmr_tpu/obs/fleetobs.py FleetHealthWatch over the beat-merged
#: registry) extend the same vocabulary: worker_outlier_latency = one
#: worker's window p95 beyond factor x the median of its peers;
#: partition_skew = one worker drawing a window request share beyond
#: factor x the fair share; fleet_mfu_drop = cluster-summed window
#: FLOP/s below factor x rolling baseline; beat_gap = a live worker's
#: last heartbeat older than factor x the beat interval.
FLEET_ANOMALY_KINDS = (
    "worker_outlier_latency",
    "partition_skew",
    "fleet_mfu_drop",
    "beat_gap",
)

ANOMALY_KINDS = (
    "recompile_storm",
    "latency_regression",
    "queue_saturation",
    "cache_hit_collapse",
    "mfu_drop",
) + FLEET_ANOMALY_KINDS


def validate_anomaly(rec: dict) -> List[str]:
    """Structural check of one anomaly record (gate_refused-style cause
    record: closed-vocabulary kind + message + numeric evidence)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return [f"not a dict: {type(rec).__name__}"]
    if rec.get("anomaly") not in ANOMALY_KINDS:
        problems.append(f"anomaly: bad kind {rec.get('anomaly')!r}")
    if not isinstance(rec.get("message"), str) or not rec.get("message"):
        problems.append("message: not a non-empty string")
    if not isinstance(rec.get("evidence"), dict):
        problems.append("evidence: not a dict")
    return problems


#: schema tag of the serving-engine health document
#: (``ServeEngine.health()``): queue depths, per-device occupancy, cache
#: stats, compile-event tallies, and the anomalies the health watch
#: fired this pass — the admission-control input ROADMAP item 3
#: consumes. The heartbeat writer appends one per interval as JSONL.
HEALTH_REPORT_SCHEMA = "health_report/v1"


def validate_health_report(doc: dict) -> List[str]:
    """Structural check of a health_report/v1 document; returns a list
    of problems (empty == valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    problems += _validate_quant_attachment(doc)
    if doc.get("schema") != HEALTH_REPORT_SCHEMA:
        problems.append(
            f"schema != {HEALTH_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    for key, typ in (("ts", (int, float)), ("uptime_s", (int, float)),
                     ("closed", bool), ("inflight", int)):
        if not isinstance(doc.get(key), typ) or (
            typ is int and isinstance(doc.get(key), bool)
        ):
            problems.append(f"{key}: not a {typ}")
    queues = doc.get("queues")
    if not isinstance(queues, dict) or not isinstance(
        queues.get("pending"), int
    ) or not isinstance(queues.get("per_bucket"), dict):
        problems.append("queues: missing pending/per_bucket")
    if not isinstance(doc.get("devices"), list):
        problems.append("devices: not a list")
    if not isinstance(doc.get("per_device_batches"), dict):
        problems.append("per_device_batches: not a dict")
    caches = doc.get("caches")
    if not isinstance(caches, dict):
        problems.append("caches: not a dict")
    else:
        for which in ("result", "feature"):
            sub = caches.get(which)
            if not isinstance(sub, dict) or not all(
                k in sub for k in ("hits", "misses", "evictions")
            ):
                problems.append(
                    f"caches.{which}: missing hits/misses/evictions"
                )
    counters = doc.get("counters")
    if not isinstance(counters, dict) or not all(
        isinstance(v, (int, float)) and not isinstance(v, bool)
        for v in counters.values()
    ):
        problems.append("counters: not a dict of numbers")
    compile_rec = doc.get("compile")
    if not isinstance(compile_rec, dict) or not all(
        isinstance(compile_rec.get(k), int)
        for k in ("total", "cold", "key_change")
    ):
        problems.append("compile: missing total/cold/key_change ints")
    anomalies = doc.get("anomalies")
    if not isinstance(anomalies, list):
        problems.append("anomalies: not a list")
    else:
        for i, rec in enumerate(anomalies):
            problems += [f"anomalies[{i}]: {p}" for p in
                         validate_anomaly(rec)]
    # optional overload-control sections (present only when the engine
    # runs with admission/degradation enabled — the default-knobs shape
    # is exactly the PR 8 one)
    if "admission" in doc:
        adm = doc["admission"]
        if not isinstance(adm, dict) or not all(
            k in adm for k in ("enabled", "max_pending", "in_system")
        ):
            problems.append(
                "admission: missing enabled/max_pending/in_system"
            )
    if "degrade" in doc:
        deg = doc["degrade"]
        if not isinstance(deg, dict) or not isinstance(
            deg.get("level"), int
        ) or not isinstance(deg.get("steps"), list):
            problems.append("degrade: missing level/steps")
    # optional mesh-serving sections (present only under a mesh plan —
    # the default-engine shape stays byte-identical to PR 8)
    problems += _validate_mesh_attachment(doc)
    per_group = (queues or {}).get("per_group") if isinstance(
        queues, dict
    ) else None
    if per_group is not None:
        if not isinstance(per_group, dict) or not all(
            isinstance(rec, dict) and isinstance(rec.get("pending"), int)
            and isinstance(rec.get("per_bucket"), dict)
            for rec in per_group.values()
        ):
            problems.append(
                "queues.per_group: not {group: {pending, per_bucket}}"
            )
    return problems


#: schema tag of the flight-recorder probe document emitted by
#: scripts/obs_watch.py: the mfu_report from a measured tiny serve
#: workload (analytic-vs-cost_analysis FLOPs envelope checked), a
#: validated health_report + heartbeat JSONL round-trip, injected
#: recompile-storm and queue-saturation anomaly firings, and the
#: disabled-mode overhead of the whole flight layer. bench_guard wraps
#: the probe, so an error record ({"schema": ..., "error": str}) is
#: contractually valid.
FLIGHT_REPORT_SCHEMA = "flight_report/v1"


def validate_flight_report(doc: dict) -> List[str]:
    """Structural check of a flight_report/v1 document; returns a list
    of problems (empty == valid). An error record is contractually
    valid (the bench_guard wedge path)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != FLIGHT_REPORT_SCHEMA:
        problems.append(
            f"schema != {FLIGHT_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    if not isinstance(doc.get("config"), dict):
        problems.append("config: not a dict")
    problems += [f"mfu: {p}" for p in validate_mfu_report(
        doc.get("mfu") or {}
    )]
    problems += [f"health: {p}" for p in validate_health_report(
        doc.get("health") or {}
    )]
    anomalies = doc.get("anomalies")
    if not isinstance(anomalies, dict):
        problems.append("anomalies: not a dict")
    else:
        for section in ("recompile_storm", "queue_saturation"):
            recs = anomalies.get(section)
            if not isinstance(recs, list):
                problems.append(f"anomalies.{section}: not a list")
                continue
            for i, rec in enumerate(recs):
                problems += [f"anomalies.{section}[{i}]: {p}"
                             for p in validate_anomaly(rec)]
    overhead = doc.get("overhead")
    if not isinstance(overhead, dict):
        problems.append("overhead: not a dict")
    else:
        for key in ("disabled_ns_per_check", "overhead_disabled_pct"):
            if not isinstance(overhead.get(key), (int, float)):
                problems.append(f"overhead: missing {key!r}")
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("mfu_finite", "flops_envelope_ok", "health_valid",
                    "heartbeat_roundtrip", "storm_exact", "queue_exact",
                    "overhead_ok"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the fleet-observability probe document emitted by
#: scripts/fleet_obs_probe.py (plane in tmr_tpu/obs/fleetobs.py): the
#: per-worker + merged beat-folded registries with the exact
#: sum-of-deltas reconciliation, the cross-process span-chain evidence,
#: the stitched-timeline summary (per-track clock offsets + post-
#: correction monotonicity), the fleet HealthWatch firings per phase,
#: and the disabled-mode overhead of the whole plane. bench_guard wraps
#: the probe, so an error record ({"schema": ..., "error": str}) is
#: contractually valid.
FLEET_OBS_REPORT_SCHEMA = "fleet_obs_report/v1"


def validate_fleet_obs_report(doc: dict) -> List[str]:
    """Structural check of a fleet_obs_report/v1 document; returns a
    list of problems (empty == valid). An error record is contractually
    valid (the bench_guard wedge path)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != FLEET_OBS_REPORT_SCHEMA:
        problems.append(
            f"schema != {FLEET_OBS_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    if not isinstance(doc.get("config"), dict):
        problems.append("config: not a dict")
    workers = doc.get("workers")
    if not isinstance(workers, dict):
        problems.append("workers: not a dict")
    else:
        for wid, rec in workers.items():
            if not isinstance(rec, dict):
                problems.append(f"workers[{wid!r}]: not a dict")
                continue
            for key in ("beats", "spans"):
                if not isinstance(rec.get(key), int) or isinstance(
                    rec.get(key), bool
                ):
                    problems.append(f"workers[{wid!r}].{key}: not an int")
            clock = rec.get("clock")
            if clock is not None and (
                not isinstance(clock, dict)
                or not all(isinstance(clock.get(k), (int, float))
                           for k in ("offset_s", "err_s"))
            ):
                problems.append(
                    f"workers[{wid!r}].clock: missing offset_s/err_s"
                )
    problems += [f"merged: {p}" for p in validate_metrics_report(
        doc.get("merged") or {}
    )]
    recon = doc.get("reconciliation")
    if not isinstance(recon, dict) or not isinstance(
        recon.get("exact"), bool
    ):
        problems.append("reconciliation: missing exact bool")
    trace = doc.get("trace")
    if not isinstance(trace, dict):
        problems.append("trace: not a dict")
    else:
        for key in ("events", "tracks"):
            if not isinstance(trace.get(key), int) or isinstance(
                trace.get(key), bool
            ):
                problems.append(f"trace.{key}: not an int")
        if not isinstance(trace.get("monotone"), bool):
            problems.append("trace.monotone: not a bool")
    anomalies = doc.get("anomalies")
    if not isinstance(anomalies, dict):
        problems.append("anomalies: not a dict")
    else:
        for section, recs in anomalies.items():
            if not isinstance(recs, list):
                problems.append(f"anomalies.{section}: not a list")
                continue
            for i, rec in enumerate(recs):
                problems += [f"anomalies.{section}[{i}]: {p}"
                             for p in validate_anomaly(rec)]
    if not isinstance(doc.get("beat_errors"), int) or isinstance(
        doc.get("beat_errors"), bool
    ):
        problems.append("beat_errors: not an int")
    overhead = doc.get("overhead")
    if not isinstance(overhead, dict):
        problems.append("overhead: not a dict")
    else:
        for key in ("disabled_ns_per_check", "overhead_disabled_pct"):
            if not isinstance(overhead.get(key), (int, float)):
                problems.append(f"overhead: missing {key!r}")
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("span_chain_complete", "metrics_reconciled",
                    "stitched_monotone", "slow_worker_exact",
                    "beat_gap_exact", "calm_quiet", "overhead_ok"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the bench-history trend document emitted by
#: scripts/bench_trend.py (core reader in tmr_tpu/utils/bench_trend.py):
#: the committed BENCH_r0*.json driver records plus the live bench
#: files, reduced to one headline/MFU trajectory with regressions
#: between measured rounds flagged. bench.py embeds one per round
#: behind TMR_BENCH_TREND=1.
BENCH_TREND_SCHEMA = "bench_trend/v1"

#: closed per-round provenance vocabulary in a bench_trend/v1 document:
#: "measured" = the round's probe produced its own number, "carried" =
#: the record promoted an older committed measurement (bench.py's
#: ``carried: true`` outage path), "error" = no usable number at all.
BENCH_TREND_SOURCES = ("measured", "carried", "error")


def validate_bench_trend(doc: dict) -> List[str]:
    """Structural check of a bench_trend/v1 document; returns a list of
    problems (empty == valid). An error record ({"schema": ...,
    "error": str}) is contractually valid."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != BENCH_TREND_SCHEMA:
        problems.append(
            f"schema != {BENCH_TREND_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    rounds = doc.get("rounds")
    if not isinstance(rounds, list) or not rounds:
        problems.append("rounds: not a non-empty list")
        rounds = []
    for i, r in enumerate(rounds):
        where = f"rounds[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("label", "source", "value", "mfu"):
            if key not in r:
                problems.append(f"{where}: missing {key!r}")
        if r.get("source") not in BENCH_TREND_SOURCES:
            problems.append(f"{where}: bad source {r.get('source')!r}")
        for key in ("value", "mfu"):
            v = r.get(key)
            if v is not None and (
                not isinstance(v, (int, float)) or isinstance(v, bool)
            ):
                problems.append(f"{where}.{key}: not a number or null")
    regs = doc.get("regressions")
    if not isinstance(regs, list):
        problems.append("regressions: not a list")
        regs = []
    for i, r in enumerate(regs):
        where = f"regressions[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("field", "from_label", "to_label", "before", "after",
                    "drop_pct"):
            if key not in r:
                problems.append(f"{where}: missing {key!r}")
        if r.get("field") not in ("value", "mfu"):
            problems.append(f"{where}: bad field {r.get('field')!r}")
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("measured_rounds", "regressed"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


def validate_map_report(doc: dict) -> List[str]:
    """Structural check of a map_report/v1 document; returns a list of
    problems (empty == valid). Dependency-free so CI harnesses can gate on
    the report without importing the extraction stack."""
    problems: List[str] = []
    problems += _validate_metrics_attachment(doc)
    problems += _validate_mfu_attachment(doc)
    if doc.get("schema") != MAP_REPORT_SCHEMA:
        problems.append(f"schema != {MAP_REPORT_SCHEMA}: {doc.get('schema')!r}")
    shards = doc.get("shards")
    if not isinstance(shards, list):
        return problems + ["shards: not a list"]
    for i, rec in enumerate(shards):
        where = f"shards[{i}]"
        if not isinstance(rec, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("shard", "status", "attempts", "images",
                    "skipped_images", "nonfinite_images", "wall_s"):
            if key not in rec:
                problems.append(f"{where}: missing {key!r}")
        if rec.get("status") not in MAP_SHARD_STATUSES:
            problems.append(f"{where}: bad status {rec.get('status')!r}")
        causes = rec.get("causes", ())
        if not isinstance(causes, (list, tuple)):
            problems.append(f"{where}.causes: not a list")
            causes = ()
        for j, cause in enumerate(causes):
            if not isinstance(cause, dict):
                problems.append(f"{where}.causes[{j}]: not a dict")
            elif cause.get("cause") not in MAP_FAILURE_CAUSES:
                problems.append(
                    f"{where}.causes[{j}]: bad cause {cause.get('cause')!r}"
                )
    totals = doc.get("totals")
    if not isinstance(totals, dict):
        problems.append("totals: not a dict")
    else:
        for key in ("shards", "ok", "quarantined", "resumed", "images",
                    "skipped_images", "nonfinite_images", "retries"):
            if key not in totals:
                problems.append(f"totals: missing {key!r}")
    for key in ("quarantined", "resumed"):
        if not isinstance(doc.get(key), list):
            problems.append(f"{key}: not a list")
    return problems

#: schema tag of the elastic map-phase run document
#: (parallel/elastic.py ElasticCoordinator.report()): per-shard final
#: status + winning worker/epoch, per-worker commit/failure tallies with
#: drain flags, every lease reassignment with a closed-vocab cause,
#: every fenced (stale-epoch) commit rejection, and totals that must
#: reconcile exactly — shards = committed + resumed + quarantined, with
#: reassigned shards counted once under whoever finally committed them.
ELASTIC_REPORT_SCHEMA = "elastic_report/v1"

#: closed reassignment-cause vocabulary shared by the lease-service
#: clients (elastic_report/v1 map shards, elastic_serve_report/v1
#: traffic partitions): stale_heartbeat = the lease's heartbeat went
#: stale past the TTL (dead or paused worker); worker_exit = the
#: worker left while it held the lease — a dropped control connection
#: (kill -9 / crash) or, for fleet workers, a clean ``bye`` that still
#: held partitions (serve leases are held for the worker's lifetime,
#: so a graceful leave releases through the same path); straggler = a
#: speculative duplicate lease was
#: issued because the shard's runtime exceeded the rolling-median-based
#: bound; poison_worker = the worker reported the resource failed
#: (after N distinct such failures the worker is drained); scale_out =
#: a traffic partition moved to a newly recruited serve worker to
#: spread load (fleet rebalance-on-join — never emitted by the map
#: client).
ELASTIC_REASSIGN_CAUSES = (
    "stale_heartbeat", "worker_exit", "straggler", "poison_worker",
    "scale_out",
)

#: the MAP client's subset: validate_elastic_report stays exactly as
#: tight as before the fleet landed — a map-shard reassignment tagged
#: scale_out is a drift the validator must still catch (only the fleet
#: section validator accepts the full shared vocabulary)
MAP_REASSIGN_CAUSES = (
    "stale_heartbeat", "worker_exit", "straggler", "poison_worker",
)

#: closed final per-shard status vocabulary in an elastic_report/v1
ELASTIC_SHARD_STATUSES = ("committed", "resumed", "quarantined")

#: closed fence-op vocabulary: where a stale-epoch writer was rejected
#: ("precommit" = before its marker was written — the normal path;
#: "commit" = at result submission, the narrow in-flight race window)
ELASTIC_FENCE_OPS = ("precommit", "commit")


def validate_elastic_report(doc: dict) -> List[str]:
    """Structural + reconciliation check of an elastic_report/v1
    document; returns a list of problems (empty == valid).
    Dependency-free like the other validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    problems += _validate_metrics_attachment(doc)
    if doc.get("schema") != ELASTIC_REPORT_SCHEMA:
        problems.append(
            f"schema != {ELASTIC_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    shards = doc.get("shards")
    if not isinstance(shards, list):
        problems.append("shards: not a list")
        shards = []
    by_status = {s: 0 for s in ELASTIC_SHARD_STATUSES}
    for i, rec in enumerate(shards):
        where = f"shards[{i}]"
        if not isinstance(rec, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("index", "shard", "status", "worker", "epoch",
                    "assignments", "failures", "images", "wall_s"):
            if key not in rec:
                problems.append(f"{where}: missing {key!r}")
        status = rec.get("status")
        if status not in ELASTIC_SHARD_STATUSES:
            problems.append(f"{where}: bad status {status!r}")
        else:
            by_status[status] += 1
        if status == "committed" and not rec.get("worker"):
            problems.append(f"{where}: committed without a worker")
        fails = rec.get("failures", ())
        if not isinstance(fails, (list, tuple)):
            problems.append(f"{where}.failures: not a list")
            fails = ()
        for j, f in enumerate(fails):
            if not isinstance(f, dict) or "worker" not in f:
                problems.append(f"{where}.failures[{j}]: missing worker")
    workers = doc.get("workers")
    if not isinstance(workers, dict):
        problems.append("workers: not a dict")
        workers = {}
    for wid, w in workers.items():
        if not isinstance(w, dict) or not all(
            k in w for k in ("committed", "failed_shards", "drained")
        ):
            problems.append(
                f"workers[{wid!r}]: missing committed/failed_shards/"
                "drained"
            )
    reassignments = doc.get("reassignments")
    if not isinstance(reassignments, list):
        problems.append("reassignments: not a list")
        reassignments = []
    for i, r in enumerate(reassignments):
        where = f"reassignments[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("shard", "worker", "epoch", "cause"):
            if key not in r:
                problems.append(f"{where}: missing {key!r}")
        if r.get("cause") not in MAP_REASSIGN_CAUSES:
            problems.append(f"{where}: bad cause {r.get('cause')!r}")
    fenced = doc.get("fenced_rejections")
    if not isinstance(fenced, list):
        problems.append("fenced_rejections: not a list")
        fenced = []
    for i, r in enumerate(fenced):
        where = f"fenced_rejections[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("shard", "worker", "epoch", "op"):
            if key not in r:
                problems.append(f"{where}: missing {key!r}")
        if r.get("op") not in ELASTIC_FENCE_OPS:
            problems.append(f"{where}: bad op {r.get('op')!r}")
    for key in ("quarantined", "resumed"):
        if not isinstance(doc.get(key), list):
            problems.append(f"{key}: not a list")
    totals = doc.get("totals")
    if not isinstance(totals, dict):
        problems.append("totals: not a dict")
    else:
        for key in ("shards", "committed", "resumed", "quarantined",
                    "reassignments", "fenced_rejections", "workers",
                    "drained_workers", "wall_s"):
            if key not in totals:
                problems.append(f"totals: missing {key!r}")
        # exact reconciliation: every shard settled exactly once, and the
        # totals agree with the per-shard records and event lists — a
        # reassigned-and-committed shard counts once, under its winner
        if isinstance(shards, list) and shards and not problems:
            if totals.get("shards") != len(shards):
                problems.append("totals.shards != len(shards)")
            for status in ELASTIC_SHARD_STATUSES:
                if totals.get(status) != by_status[status]:
                    problems.append(
                        f"totals.{status} != per-shard {status} count"
                    )
            if (totals.get("committed", 0) + totals.get("resumed", 0)
                    + totals.get("quarantined", 0)) != len(shards):
                problems.append(
                    "totals: committed + resumed + quarantined != shards"
                )
            if totals.get("reassignments") != len(reassignments):
                problems.append(
                    "totals.reassignments != len(reassignments)"
                )
            if totals.get("fenced_rejections") != len(fenced):
                problems.append(
                    "totals.fenced_rejections != len(fenced_rejections)"
                )
    return problems


#: schema tag of the elastic-serving probe document emitted by
#: scripts/elastic_serve_probe.py (the chaos_probe --elastic story
#: applied to the serve fleet, serve/fleet.py): per-phase fleet state
#: (partition leases, workers, cause-tagged reassignments, fenced
#: lease rejections) plus the exactly-once result accounting —
#: ``offered == completed + rejected + shed + errors`` EXACTLY, zero
#: double-served request ids, fenced late results counted — rebalance
#: latency, and the recruitment round. bench_guard wraps the probe, so
#: an error record ({"schema": ..., "error": str}) is contractually
#: valid; scripts/bench_trend.py --fleet rc-gates on the
#: zero-double-served and reconciliation fields.
ELASTIC_SERVE_REPORT_SCHEMA = "elastic_serve_report/v1"

#: the exactly-once accounting fields every fleet/probe accounting
#: record must carry as non-negative ints; the first four reconcile
#: exactly against ``offered``
FLEET_ACCOUNTING_KEYS = (
    "offered", "completed", "rejected", "shed", "errors",
    "resubmitted", "fenced_results", "late_results", "double_served",
)


def _validate_fleet_accounting(acc, where: str) -> List[str]:
    """The exactly-once contract as a validation rule: every key a
    non-negative int and offered == completed + rejected + shed +
    errors EXACTLY (resubmissions/fenced/late commits are bookkeeping,
    never extra terminals)."""
    if not isinstance(acc, dict):
        return [f"{where}: not a dict"]
    problems: List[str] = []
    for key in FLEET_ACCOUNTING_KEYS:
        v = acc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{where}.{key}: not a non-negative int")
    if not problems and acc["offered"] != (
        acc["completed"] + acc["rejected"] + acc["shed"] + acc["errors"]
    ):
        problems.append(
            f"{where}: offered != completed + rejected + shed + errors"
        )
    return problems


def _validate_fleet_section(fleet, where: str) -> List[str]:
    """One ServeFleet.report() document (embedded per probe phase)."""
    if not isinstance(fleet, dict):
        return [f"{where}: not a dict"]
    problems: List[str] = []
    partitions = fleet.get("partitions")
    if not isinstance(partitions, list) or not partitions:
        problems.append(f"{where}.partitions: not a non-empty list")
        partitions = []
    for i, rec in enumerate(partitions):
        sub = f"{where}.partitions[{i}]"
        if not isinstance(rec, dict):
            problems.append(f"{sub}: not a dict")
            continue
        for key in ("index", "partition", "status", "worker", "epoch",
                    "assignments"):
            if key not in rec:
                problems.append(f"{sub}: missing {key!r}")
    if not isinstance(fleet.get("workers"), dict):
        problems.append(f"{where}.workers: not a dict")
    for section, vocab_key, vocab in (
        ("reassignments", "cause", ELASTIC_REASSIGN_CAUSES),
        ("fenced_rejections", "op", ELASTIC_FENCE_OPS),
    ):
        recs = fleet.get(section)
        if not isinstance(recs, list):
            problems.append(f"{where}.{section}: not a list")
            continue
        for i, r in enumerate(recs):
            sub = f"{where}.{section}[{i}]"
            if not isinstance(r, dict):
                problems.append(f"{sub}: not a dict")
                continue
            for key in ("partition", "worker", "epoch", vocab_key):
                if key not in r:
                    problems.append(f"{sub}: missing {key!r}")
            if r.get(vocab_key) not in vocab:
                problems.append(
                    f"{sub}: bad {vocab_key} {r.get(vocab_key)!r}"
                )
    problems += _validate_fleet_accounting(
        fleet.get("accounting"), f"{where}.accounting"
    )
    return problems


def validate_elastic_serve_report(doc: dict) -> List[str]:
    """Structural + reconciliation check of an elastic_serve_report/v1
    document; returns a list of problems (empty == valid). An error
    record is contractually valid (the bench_guard wedge path).
    Dependency-free like the other validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != ELASTIC_SERVE_REPORT_SCHEMA:
        problems.append(
            f"schema != {ELASTIC_SERVE_REPORT_SCHEMA}: "
            f"{doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    if not isinstance(doc.get("config"), dict):
        problems.append("config: not a dict")
    phases = doc.get("phases")
    if not isinstance(phases, list) or not phases:
        problems.append("phases: not a non-empty list")
        phases = []
    for i, phase in enumerate(phases):
        where = f"phases[{i}]"
        if not isinstance(phase, dict):
            problems.append(f"{where}: not a dict")
            continue
        if not isinstance(phase.get("name"), str) or not phase["name"]:
            problems.append(f"{where}.name: not a non-empty string")
        if not isinstance(phase.get("offered"), int) \
                or isinstance(phase.get("offered"), bool):
            problems.append(f"{where}.offered: not an int")
        outcomes = phase.get("outcomes")
        if not isinstance(outcomes, dict) or not all(
            isinstance(outcomes.get(k), int)
            and not isinstance(outcomes.get(k), bool)
            for k in ("completed", "rejected", "shed", "errors")
        ):
            problems.append(
                f"{where}.outcomes: missing completed/rejected/shed/"
                "errors ints"
            )
        elif isinstance(phase.get("offered"), int) and \
                sum(outcomes[k] for k in ("completed", "rejected",
                                          "shed", "errors")) \
                != phase["offered"]:
            problems.append(
                f"{where}: probe-side outcomes do not reconcile with "
                "offered"
            )
        problems += _validate_fleet_section(phase.get("fleet"),
                                            f"{where}.fleet")
    problems += _validate_fleet_accounting(doc.get("accounting"),
                                           "accounting")
    rebalance = doc.get("rebalance")
    if not isinstance(rebalance, dict) or not all(
        isinstance(rebalance.get(k), (int, float))
        and not isinstance(rebalance.get(k), bool)
        for k in ("count", "max_latency_s", "bound_s")
    ):
        problems.append("rebalance: missing count/max_latency_s/bound_s")
    recruit = doc.get("recruitment")
    if not isinstance(recruit, dict) or not all(
        isinstance(recruit.get(k), int)
        and not isinstance(recruit.get(k), bool)
        for k in ("rounds", "workers_before", "workers_after",
                  "degrade_level", "degrade_max_seen")
    ):
        problems.append(
            "recruitment: missing rounds/workers_before/workers_after/"
            "degrade_level/degrade_max_seen ints"
        )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("futures_terminal", "zero_double_served",
                    "accounting_exact_probe", "accounting_exact_fleet",
                    "results_correct", "fenced_late_result",
                    "rebalance_bounded", "recruitment_absorbed",
                    "degrade_level0"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the serve-tier chaos gauntlet emitted by
#: scripts/serve_chaos_probe.py (the chaos_probe story extended past
#: the map/elastic layer into the replicated gallery fleet,
#: serve/gallery_fleet.py): phase-by-phase pattern accounting across
#: repeated primary kill -9s (zero registered patterns lost — journal
#: + replica promotion), healthy-fleet fan-out-vs-single-bank byte
#: equality, and a fault ledger proving every injected serve-tier
#: fault (severed links, corrupt replica payloads, beats delayed past
#: the lease window) was observed, accounted for, and surfaced as a
#: labeled degrade step. bench_guard wraps the probe, so an error
#: record ({"schema": ..., "error": str}) is contractually valid;
#: scripts/bench_trend.py --chaos rc-gates fail-closed on the
#: zero-loss and all-faults-accounted invariants.
SERVE_CHAOS_REPORT_SCHEMA = "serve_chaos_report/v1"

#: the closed serve-tier fault-point vocabulary a serve_chaos_report
#: may inject/observe — the serve slice of faults.POINTS
SERVE_CHAOS_FAULT_POINTS = (
    "serve.link", "gallery.replica", "gallery.beat", "journal",
)

#: the checks every serve_chaos_report/v1 must carry — the probe's
#: acceptance invariants, each a bool (rc-gated by the probe itself
#: and re-gated fail-closed by bench_trend --chaos)
SERVE_CHAOS_CHECK_KEYS = (
    "zero_patterns_lost", "fanout_byte_identical",
    "all_faults_observed", "all_faults_accounted",
    "degraded_exactly_labeled", "degrade_heals",
    "replication_recovered", "env_schedule_delivered",
)


def validate_serve_chaos_report(doc: dict) -> List[str]:
    """Structural + reconciliation check of a serve_chaos_report/v1
    document; returns a list of problems (empty == valid). An error
    record is contractually valid (the bench_guard wedge path).
    Dependency-free like the other validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != SERVE_CHAOS_REPORT_SCHEMA:
        problems.append(
            f"schema != {SERVE_CHAOS_REPORT_SCHEMA}: "
            f"{doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    config = doc.get("config")
    if not isinstance(config, dict):
        problems.append("config: not a dict")
    else:
        for key in ("shards", "workers", "replicas", "patterns"):
            v = config.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                problems.append(f"config.{key}: not a positive int")
    phases = doc.get("phases")
    if not isinstance(phases, list) or not phases:
        problems.append("phases: not a non-empty list")
        phases = []
    for i, phase in enumerate(phases):
        where = f"phases[{i}]"
        if not isinstance(phase, dict):
            problems.append(f"{where}: not a dict")
            continue
        if not isinstance(phase.get("name"), str) or not phase["name"]:
            problems.append(f"{where}.name: not a non-empty string")
        if not isinstance(phase.get("ok"), bool):
            problems.append(f"{where}.ok: not a bool")
    patterns = doc.get("patterns")
    if not isinstance(patterns, dict):
        problems.append("patterns: not a dict")
    else:
        for key in ("registered", "survived"):
            v = patterns.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(
                    f"patterns.{key}: not a non-negative int"
                )
        lost = patterns.get("lost")
        if not isinstance(lost, list):
            problems.append("patterns.lost: not a list")
        elif not problems and isinstance(patterns.get("registered"), int):
            # exact pattern reconciliation: every registered pattern is
            # either survived or named in the lost list — no third bin
            if patterns["registered"] != patterns["survived"] + len(lost):
                problems.append(
                    "patterns: registered != survived + len(lost)"
                )
    kills = doc.get("kills")
    if not isinstance(kills, dict) or not all(
        isinstance(kills.get(k), int) and not isinstance(kills.get(k),
                                                         bool)
        for k in ("rounds", "workers_killed")
    ):
        problems.append("kills: missing rounds/workers_killed ints")
    elif kills["rounds"] < 1:
        problems.append("kills.rounds: no kill rounds ran")
    faults_sec = doc.get("faults")
    if not isinstance(faults_sec, dict):
        problems.append("faults: not a dict")
    else:
        injected = faults_sec.get("injected")
        if not isinstance(injected, list) or not injected:
            problems.append("faults.injected: not a non-empty list")
            injected = []
        inj_points = set()
        for i, rec in enumerate(injected):
            where = f"faults.injected[{i}]"
            if not isinstance(rec, dict):
                problems.append(f"{where}: not a dict")
                continue
            point = rec.get("point")
            if point not in SERVE_CHAOS_FAULT_POINTS:
                problems.append(f"{where}.point: bad point {point!r}")
            else:
                inj_points.add(point)
            if not isinstance(rec.get("schedule"), str) \
                    or not rec["schedule"]:
                problems.append(
                    f"{where}.schedule: not a non-empty string"
                )
            for key in ("fired", "accounted"):
                v = rec.get(key)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    problems.append(
                        f"{where}.{key}: not a non-negative int"
                    )
        observed = faults_sec.get("observed")
        if not isinstance(observed, dict):
            problems.append("faults.observed: not a dict")
        else:
            for point, n in observed.items():
                if point not in SERVE_CHAOS_FAULT_POINTS:
                    problems.append(
                        f"faults.observed: bad point {point!r}"
                    )
                if not isinstance(n, int) or isinstance(n, bool) \
                        or n < 0:
                    problems.append(
                        f"faults.observed[{point!r}]: not a "
                        "non-negative int"
                    )
            # every injected point must have been OBSERVED firing at
            # least once — a schedule that never fired proves nothing
            for point in inj_points:
                if not observed.get(point):
                    problems.append(
                        f"faults: injected point {point!r} never "
                        "observed firing"
                    )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in SERVE_CHAOS_CHECK_KEYS:
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the serving-layer benchmark document emitted by
#: scripts/serve_bench.py (offered-load sweep over tmr_tpu/serve): per-
#: workload throughput + latency percentiles + batch-occupancy histogram +
#: cache hit rates, plus the acceptance checks (speedup vs the sequential
#: Predictor loop, bitwise exactness, p99 bound, cache hit). bench_guard
#: wraps the script, so a wedged tunnel yields {"schema": ..., "error":
#: ...} — also a valid document per ``validate_serve_report``.
SERVE_REPORT_SCHEMA = "serve_report/v1"

#: closed workload-mode vocabulary in a serve_report/v1 document
SERVE_WORKLOAD_MODES = ("closed", "open")


#: closed vocabularies of the optional ``quant`` provenance attachment
#: (engine stats()/health() and serve_report/v1): which numerics tier
#: the serving programs ran — "mode" is the in-program TMR_QUANT arm,
#: "storage" whether the param tree itself was offline-quantized
#: (TMR_QUANT_STORAGE). Absent = fully exact weights.
QUANT_STAMP_MODES = ("off", "int8")


def _validate_quant_attachment(doc: dict) -> List[str]:
    """Optional ``quant`` attachment: results served from a quantized
    (and/or storage-quantized) engine carry their numerics provenance
    the way degraded results carry ``degrade_steps``."""
    if "quant" not in doc:
        return []
    q = doc["quant"]
    if not isinstance(q, dict):
        return ["quant: not a dict"]
    problems: List[str] = []
    if q.get("mode") not in QUANT_STAMP_MODES:
        problems.append(f"quant.mode: bad value {q.get('mode')!r}")
    if q.get("storage") not in QUANT_STAMP_MODES:
        problems.append(f"quant.storage: bad value {q.get('storage')!r}")
    if q.get("storage") == "int8":
        if not isinstance(q.get("digest"), str) or not q.get("digest"):
            problems.append("quant.digest: not a non-empty string under "
                            "storage=int8")
        for key in ("quantized_leaves", "weight_bytes",
                    "f32_weight_bytes"):
            v = q.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                problems.append(f"quant.{key}: not a positive int")
    return problems


def _validate_mesh_attachment(doc: dict) -> List[str]:
    """Optional ``mesh`` attachment of a serve_report/v1 (and the
    engine's health/stats views): the serving-mesh description one
    sweep round ran under — spec string, axis shape, axis names, and
    the replica groups by device. Absent = the unsharded engine."""
    if "mesh" not in doc:
        return []
    problems: List[str] = []
    mesh = doc["mesh"]
    if not isinstance(mesh, dict):
        return ["mesh: not a dict"]
    if not isinstance(mesh.get("spec"), str) or not mesh.get("spec"):
        problems.append("mesh.spec: not a non-empty string")
    shape = mesh.get("shape")
    if not isinstance(shape, dict) or not shape or not all(
        isinstance(v, int) and not isinstance(v, bool) and v >= 1
        for v in shape.values()
    ):
        problems.append("mesh.shape: not a {axis: size>=1} dict")
    names = mesh.get("axis_names")
    if not isinstance(names, list) or not all(
        isinstance(n, str) for n in names
    ):
        problems.append("mesh.axis_names: not a list of strings")
    groups = mesh.get("replica_groups")
    if not isinstance(groups, list) or not groups or not all(
        isinstance(g, list) and g and all(isinstance(d, str) for d in g)
        for g in groups
    ):
        problems.append(
            "mesh.replica_groups: not a non-empty list of non-empty "
            "device-string lists"
        )
    return problems


def validate_serve_report(doc: dict) -> List[str]:
    """Structural check of a serve_report/v1 document; returns a list of
    problems (empty == valid). Dependency-free so CI harnesses can gate on
    the report without importing the serving stack. An error record
    ({"schema": ..., "error": str}) is contractually valid."""
    problems: List[str] = []
    problems += _validate_metrics_attachment(doc)
    problems += _validate_mfu_attachment(doc)
    problems += _validate_mesh_attachment(doc)
    problems += _validate_quant_attachment(doc)
    if doc.get("schema") != SERVE_REPORT_SCHEMA:
        problems.append(
            f"schema != {SERVE_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("config: not a dict")
    else:
        for key in ("batch", "max_wait_ms", "image_size"):
            if key not in cfg:
                problems.append(f"config: missing {key!r}")
    workloads = doc.get("workloads")
    if not isinstance(workloads, list) or not workloads:
        problems.append("workloads: not a non-empty list")
        workloads = []
    for i, w in enumerate(workloads):
        where = f"workloads[{i}]"
        if not isinstance(w, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("name", "mode", "requests", "throughput_img_per_sec",
                    "latency_ms", "batch_occupancy", "cache"):
            if key not in w:
                problems.append(f"{where}: missing {key!r}")
        if w.get("mode") not in SERVE_WORKLOAD_MODES:
            problems.append(f"{where}: bad mode {w.get('mode')!r}")
        lat = w.get("latency_ms", {})
        if not isinstance(lat, dict):
            problems.append(f"{where}.latency_ms: not a dict")
        else:
            for q in ("p50", "p95", "p99"):
                if not isinstance(lat.get(q), (int, float)):
                    problems.append(f"{where}.latency_ms: missing {q!r}")
        occ = w.get("batch_occupancy", {})
        if not isinstance(occ, dict) or not all(
            isinstance(v, int) for v in occ.values()
        ):
            problems.append(f"{where}.batch_occupancy: not {{size: count}}")
        cache = w.get("cache", {})
        if not isinstance(cache, dict):
            problems.append(f"{where}.cache: not a dict")
        else:
            for which in ("result_cache", "feature_cache"):
                sub = cache.get(which)
                if not isinstance(sub, dict) or not all(
                    k in sub for k in ("hits", "misses", "evictions")
                ):
                    problems.append(
                        f"{where}.cache.{which}: missing hits/misses/"
                        "evictions"
                    )
        # optional per-workload admission/shed/degrade tallies (attached
        # by serve_bench since the overload PR so open-loop rounds under
        # pressure stay interpretable; absent on older documents)
        if "admission" in w:
            adm = w["admission"]
            if not isinstance(adm, dict):
                problems.append(f"{where}.admission: not a dict")
            else:
                for key in ("rejected", "shed", "degraded",
                            "reject_rate"):
                    v = adm.get(key)
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool):
                        problems.append(
                            f"{where}.admission: missing {key!r}"
                        )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("speedup_vs_sequential", "speedup_ok", "exact_match",
                    "p99_bounded", "cache_hit"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the gallery-tier benchmark document emitted by
#: scripts/gallery_bench.py (tmr_tpu/serve/gallery.py): patterns×frames
#: throughput of the one-backbone-pass gallery tier vs the N-loop of
#: predict_multi_exemplar on identical (frame, pattern) pairs, the
#: backbone-amortization evidence (devtime program-call counts:
#: backbone executions == frames, never frames×N), the fused-arm
#: bitwise-exactness pin, and the coarse-prefilter sweep
#: (recall-vs-full-match + full-match invocation cut per top-k rung,
#: with the elected winner). bench_guard wraps the script, so an error
#: record ({"schema": ..., "error": str}) is contractually valid;
#: scripts/bench_trend.py --gallery rc-gates on exactness +
#: backbone-amortization + the prefilter checks.
GALLERY_REPORT_SCHEMA = "gallery_report/v1"

#: the boolean acceptance checks a usable gallery_report/v1 must carry
GALLERY_REPORT_CHECKS = (
    "bitwise_exact", "backbone_amortized", "prefilter_recall_ok",
    "prefilter_cut_ok",
)

#: the boolean checks the OPTIONAL ``n_sweep`` section (the
#: catalog-scale sketch-index sweep, scripts/gallery_bench.py --sweep)
#: must carry when present; legacy documents without the section stay
#: valid
GALLERY_SWEEP_CHECKS = (
    "index_sublinear", "index_recall_ok", "index_off_exact",
)


def validate_gallery_report(doc: dict) -> List[str]:
    """Structural check of a gallery_report/v1 document; returns a list
    of problems (empty == valid). An error record is contractually
    valid (the bench_guard wedge path). Dependency-free like the other
    validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != GALLERY_REPORT_SCHEMA:
        problems.append(
            f"schema != {GALLERY_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("config: not a dict")
    else:
        for key in ("image_size", "patterns", "frames"):
            v = cfg.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                problems.append(f"config.{key}: not a positive int")
    bank = doc.get("bank")
    if not isinstance(bank, dict) or not isinstance(
        bank.get("groups"), list
    ):
        problems.append("bank: missing groups list")
    tput = doc.get("throughput")
    if not isinstance(tput, dict):
        problems.append("throughput: not a dict")
    else:
        for key in ("gallery_pattern_frames_per_sec",
                    "n_loop_pattern_frames_per_sec", "speedup"):
            v = tput.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(f"throughput.{key}: not a number")
    bb = doc.get("backbone")
    if not isinstance(bb, dict):
        problems.append("backbone: not a dict")
    else:
        for key in ("frames", "executions", "pattern_frame_pairs"):
            v = bb.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"backbone.{key}: not a non-neg int")
        if not isinstance(bb.get("by_program"), dict):
            problems.append("backbone.by_program: not a dict")
    pre = doc.get("prefilter")
    if not isinstance(pre, dict):
        problems.append("prefilter: not a dict")
    else:
        rungs = pre.get("rungs")
        if not isinstance(rungs, list):
            problems.append("prefilter.rungs: not a list")
        else:
            for i, r in enumerate(rungs):
                where = f"prefilter.rungs[{i}]"
                if not isinstance(r, dict):
                    problems.append(f"{where}: not a dict")
                    continue
                for key in ("topk", "recall", "invocation_cut",
                            "full_matches"):
                    if key not in r:
                        problems.append(f"{where}: missing {key!r}")
        elected = pre.get("elected_topk")
        if elected is not None and (
            not isinstance(elected, int) or isinstance(elected, bool)
            or elected <= 0
        ):
            problems.append(
                "prefilter.elected_topk: not a positive int or null"
            )
    sweep = doc.get("n_sweep")
    if sweep is not None:  # OPTIONAL: only --sweep runs carry it
        if not isinstance(sweep, dict):
            problems.append("n_sweep: not a dict")
        else:
            pts = sweep.get("points")
            if not isinstance(pts, list) or not pts:
                problems.append("n_sweep.points: not a non-empty list")
                pts = []
            for i, p in enumerate(pts):
                where = f"n_sweep.points[{i}]"
                if not isinstance(p, dict):
                    problems.append(f"{where}: not a dict")
                    continue
                v = p.get("n")
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v <= 0:
                    problems.append(f"{where}.n: not a positive int")
                for key in ("linear_ms", "index_ms"):
                    v = p.get(key)
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool) or v < 0:
                        problems.append(
                            f"{where}.{key}: not a non-negative number"
                        )
                v = p.get("recall")
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or not 0.0 <= v <= 1.0:
                    problems.append(f"{where}.recall: not in [0, 1]")
            if not isinstance(sweep.get("fit"), dict):
                problems.append("n_sweep.fit: not a dict")
            scheck = sweep.get("checks")
            if not isinstance(scheck, dict):
                problems.append("n_sweep.checks: not a dict")
            else:
                for key in GALLERY_SWEEP_CHECKS:
                    if key not in scheck:
                        problems.append(
                            f"n_sweep.checks: missing {key!r}"
                        )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in GALLERY_REPORT_CHECKS + ("speedup_vs_n_loop",):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the streaming-video bench document emitted by
#: scripts/stream_bench.py: a synthetic bursty multi-stream workload
#: through StreamRouter (serve/streams.py) with the devtime
#: program-call witness that backbone executions ≪ frames, measured
#: frames/s vs the frame-independent path, the bitwise-exactness pin
#: on every frame the delta check called "changed", and the
#: cross-stream isolation count. bench_guard wraps the script, so an
#: error record ({"schema": ..., "error": str}) is contractually
#: valid; scripts/bench_trend.py --stream rc-gates the checks
#: fail-closed.
STREAM_REPORT_SCHEMA = "stream_report/v1"

#: the boolean acceptance checks a usable stream_report/v1 must carry
STREAM_REPORT_CHECKS = (
    "backbone_amortized", "speedup_ok", "changed_frames_exact",
    "cross_stream_isolated", "reuse_labeled",
)


def validate_stream_report(doc: dict) -> List[str]:
    """Structural check of a stream_report/v1 document; returns a list
    of problems (empty == valid). An error record is contractually
    valid (the bench_guard wedge path). Dependency-free like the other
    validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != STREAM_REPORT_SCHEMA:
        problems.append(
            f"schema != {STREAM_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("config: not a dict")
    else:
        for key in ("image_size", "streams", "frames_per_stream",
                    "frames"):
            v = cfg.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                problems.append(f"config.{key}: not a positive int")
        d = cfg.get("delta")
        if not isinstance(d, (int, float)) or isinstance(d, bool):
            problems.append("config.delta: not a number")
    tput = doc.get("throughput")
    if not isinstance(tput, dict):
        problems.append("throughput: not a dict")
    else:
        for key in ("stream_frames_per_sec",
                    "independent_frames_per_sec", "speedup"):
            v = tput.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(f"throughput.{key}: not a number")
    bb = doc.get("backbone")
    if not isinstance(bb, dict):
        problems.append("backbone: not a dict")
    else:
        for key in ("frames", "executions"):
            v = bb.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"backbone.{key}: not a non-neg int")
        if not isinstance(bb.get("by_program"), dict):
            problems.append("backbone.by_program: not a dict")
    reuse = doc.get("reuse")
    if not isinstance(reuse, dict):
        problems.append("reuse: not a dict")
    else:
        for key in ("reused_frames", "changed_frames", "first_frames"):
            v = reuse.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"reuse.{key}: not a non-neg int")
    ex = doc.get("exactness")
    if not isinstance(ex, dict):
        problems.append("exactness: not a dict")
    else:
        for key in ("changed_frames_checked", "mismatches"):
            v = ex.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"exactness.{key}: not a non-neg int")
    iso = doc.get("isolation")
    if not isinstance(iso, dict):
        problems.append("isolation: not a dict")
    else:
        v = iso.get("cross_stream_hits")
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(
                "isolation.cross_stream_hits: not a non-neg int"
            )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in STREAM_REPORT_CHECKS:
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the overload-robustness probe document emitted by
#: scripts/overload_probe.py: measured capacity, a >=5x offered-load
#: round against a bounded-admission engine (admitted-traffic latency
#: percentiles, exact reject/shed/complete accounting vs offers), a
#: deterministic deadline-shed burst, the degrade ladder's recorded
#: steps plus its auto escalation/cooldown trajectory, and a
#: mid-overload close() timing. bench_guard wraps the probe, so an
#: error record ({"schema": ..., "error": str}) is contractually valid.
OVERLOAD_REPORT_SCHEMA = "overload_report/v1"


def validate_overload_report(doc: dict) -> List[str]:
    """Structural check of an overload_report/v1 document; returns a
    list of problems (empty == valid). Dependency-free like the other
    validators; an error record is contractually valid."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != OVERLOAD_REPORT_SCHEMA:
        problems.append(
            f"schema != {OVERLOAD_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    if not isinstance(doc.get("config"), dict):
        problems.append("config: not a dict")
    cap = doc.get("capacity")
    if not isinstance(cap, dict) or not isinstance(
        cap.get("img_per_sec"), (int, float)
    ):
        problems.append("capacity: missing img_per_sec")
    over = doc.get("overload")
    if not isinstance(over, dict):
        problems.append("overload: not a dict")
    else:
        for key in ("offered", "completed", "rejected", "shed",
                    "errors", "degraded"):
            v = over.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                problems.append(f"overload.{key}: not an int")
        if not isinstance(over.get("offered_img_per_sec"), (int, float)):
            problems.append("overload.offered_img_per_sec: not a number")
        lat = over.get("latency_ms")
        if not isinstance(lat, dict) or not all(
            isinstance(lat.get(q), (int, float))
            for q in ("p50", "p95", "p99")
        ):
            problems.append("overload.latency_ms: missing p50/p95/p99")
        causes = over.get("reject_causes")
        if causes is not None and not isinstance(causes, dict):
            problems.append("overload.reject_causes: not a dict")
    close_rec = doc.get("close")
    if not isinstance(close_rec, dict) or not all(
        isinstance(close_rec.get(k), (int, float))
        for k in ("wall_s", "timeout_s")
    ):
        problems.append("close: missing wall_s/timeout_s")
    deg = doc.get("degrade")
    if not isinstance(deg, dict):
        problems.append("degrade: not a dict")
    else:
        if not isinstance(deg.get("steps_seen"), list):
            problems.append("degrade.steps_seen: not a list")
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("p99_bounded", "accounting_exact",
                    "rejected_nonzero", "shed_before_device",
                    "degrade_steps_recorded", "degrade_auto_ladder",
                    "close_bounded"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the telemetry probe document emitted by
#: scripts/obs_probe.py: per-stage span counts and span-derived
#: p50/p95/p99 for the serve pipeline and the map phase, the compile
#: events observed (kind/key/wall/cause), a metrics_report/v1 registry
#: snapshot, and the measured disabled-mode tracing overhead — the
#: before/after instrument every later perf PR reads. bench_guard wraps
#: the probe, so an error record ({"schema": ..., "error": str}) is
#: contractually valid here too.
TRACE_REPORT_SCHEMA = "trace_report/v1"

#: the serve pipeline stages obs_probe requires as spans, in pipeline
#: order — submit through future resolution, one trace id per request
TRACE_SERVE_STAGES = (
    "serve.submit",
    "serve.queue_wait",
    "serve.batch_assemble",
    "serve.stage",
    "serve.execute",
    "serve.postprocess",
    "serve.resolve",
)

#: closed compile-cause vocabulary (obs/compile.py): "cold" = first
#: program of its kind this process, "key-change" = the recompile-storm
#: signature (same kind, new key)
COMPILE_EVENT_CAUSES = ("cold", "key-change")


def validate_trace_report(doc: dict) -> List[str]:
    """Structural check of a trace_report/v1 document; returns a list of
    problems (empty == valid). An error record is contractually valid."""
    problems: List[str] = []
    if doc.get("schema") != TRACE_REPORT_SCHEMA:
        problems.append(
            f"schema != {TRACE_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    problems += _validate_metrics_attachment(doc)
    if "metrics" not in doc:
        problems.append("metrics: missing")
    if not isinstance(doc.get("config"), dict):
        problems.append("config: not a dict")
    for section in ("serve", "map"):
        sec = doc.get(section)
        if not isinstance(sec, dict):
            problems.append(f"{section}: not a dict")
            continue
        stages = sec.get("stages")
        if not isinstance(stages, dict):
            problems.append(f"{section}.stages: not a dict")
            continue
        for name, rec in stages.items():
            where = f"{section}.stages[{name!r}]"
            if not isinstance(rec, dict):
                problems.append(f"{where}: not a dict")
                continue
            for key in ("count", "p50_ms", "p95_ms", "p99_ms"):
                if not isinstance(rec.get(key), (int, float)):
                    problems.append(f"{where}: missing {key!r}")
    events = doc.get("compile_events")
    if not isinstance(events, list):
        problems.append("compile_events: not a list")
    else:
        for i, e in enumerate(events):
            where = f"compile_events[{i}]"
            if not isinstance(e, dict):
                problems.append(f"{where}: not a dict")
                continue
            for key in ("kind", "key", "wall_s", "cause"):
                if key not in e:
                    problems.append(f"{where}: missing {key!r}")
            if e.get("cause") not in COMPILE_EVENT_CAUSES:
                problems.append(f"{where}: bad cause {e.get('cause')!r}")
    overhead = doc.get("overhead")
    if not isinstance(overhead, dict):
        problems.append("overhead: not a dict")
    else:
        for key in ("disabled_ns_per_span", "overhead_disabled_pct"):
            if not isinstance(overhead.get(key), (int, float)):
                problems.append(f"overhead: missing {key!r}")
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("stages_complete", "compile_event_recorded",
                    "trace_roundtrip", "overhead_ok"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: keys a valid ``stage_breakdown`` record (bench.py embeds one per
#: round; utils/stage_bench.measure_stage_breakdown emits it): the
#: formulations that actually traced plus one ``<stage>_s`` seconds/iter
#: or ``<stage>_error`` string per tail stage. Not a standalone
#: ``*_REPORT_SCHEMA`` document — it rides inside the bench record, so it
#: carries no schema tag of its own.
STAGE_BREAKDOWN_STAGES = ("decoder_heads", "decode_tail")


def validate_stage_breakdown(doc: dict) -> List[str]:
    """Structural check of a bench ``stage_breakdown`` record; returns a
    list of problems (empty == valid). Each stage must carry EITHER its
    measured ``<stage>_s`` seconds (non-negative number) or a
    ``<stage>_error`` string — never both, never neither — alongside the
    formulation stamp (decoder_impl/quant/decode_tail) that says what the
    timing measured. A bare ``{"error": str}`` record is also valid: the
    whole harness failed before any stage could stamp (bench.py's
    fallback — the headline must survive a mid-stage wedge), so there is
    nothing stage-wise to check. Dependency-free like the report
    validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if set(doc) == {"error"}:
        if not isinstance(doc["error"], str) or not doc["error"]:
            return ["error: not a non-empty string"]
        return []
    for key, legal in (("decoder_impl", ("xla", "fused")),
                       ("quant", ("off", "int8")),
                       ("decode_tail", ("host", "device"))):
        if doc.get(key) not in legal:
            problems.append(f"{key}: {doc.get(key)!r} not in {legal}")
    # optional storage stamps (absent on pre-storage records)
    if "quant_storage" in doc and doc["quant_storage"] not in \
            QUANT_STAMP_MODES:
        problems.append(
            f"quant_storage: {doc['quant_storage']!r} not in "
            f"{QUANT_STAMP_MODES}"
        )
    if "quant_kernel" in doc and doc["quant_kernel"] not in (
        "dequant", "int8dot", "pallas"
    ):
        problems.append(f"quant_kernel: {doc['quant_kernel']!r} bad")
    for stage in STAGE_BREAKDOWN_STAGES:
        sec, err = doc.get(f"{stage}_s"), doc.get(f"{stage}_error")
        if sec is None and err is None:
            problems.append(f"{stage}: neither {stage}_s nor {stage}_error")
        elif sec is not None and err is not None:
            problems.append(f"{stage}: both {stage}_s and {stage}_error")
        elif err is None:
            if not isinstance(sec, (int, float)) or isinstance(sec, bool) \
                    or sec < 0:
                problems.append(f"{stage}_s: not a non-negative number")
        elif not isinstance(err, str) or not err:
            problems.append(f"{stage}_error: not a non-empty string")
    return problems


#: schema tag of the static-analysis + program-audit document emitted by
#: scripts/analyze.py (tmr_tpu/analysis): AST-tier findings (rule id +
#: file:line + message, suppression-baseline applied), per-rule tallies,
#: and the program-tier audit record (jaxpr invariants of the bucketed
#: production programs: no-S² attention, no-f64, quant-widen, transfer
#: guard). CI gates on ``checks.clean``.
ANALYSIS_REPORT_SCHEMA = "analysis_report/v1"


def validate_analysis_report(doc: dict) -> List[str]:
    """Structural check of an analysis_report/v1 document; returns a
    list of problems (empty == valid). An error record
    ({"schema": ..., "error": str}) is contractually valid (the
    bench_guard wrapper's wedge path). Dependency-free like the other
    validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != ANALYSIS_REPORT_SCHEMA:
        problems.append(
            f"schema != {ANALYSIS_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    rules = doc.get("rules")
    if not isinstance(rules, list) or not all(
        isinstance(r, str) for r in rules
    ) or not rules:
        problems.append("rules: not a non-empty list of rule ids")
    findings = doc.get("findings")
    if not isinstance(findings, list):
        problems.append("findings: not a list")
        findings = []
    for i, f in enumerate(findings):
        where = f"findings[{i}]"
        if not isinstance(f, dict):
            problems.append(f"{where}: not a dict")
            continue
        for key in ("rule", "file", "line", "message"):
            if key not in f:
                problems.append(f"{where}: missing {key!r}")
        if isinstance(rules, list) and rules \
                and f.get("rule") not in rules:
            problems.append(f"{where}: unknown rule {f.get('rule')!r}")
    if not isinstance(doc.get("baselined_count"), int):
        problems.append("baselined_count: not an int")
    if not isinstance(doc.get("counts_by_rule"), dict):
        problems.append("counts_by_rule: not a dict")
    prog = doc.get("program_audit")
    if prog is not None:
        if not isinstance(prog, dict):
            problems.append("program_audit: not a dict")
        else:
            for key in ("platform", "states", "problems", "ok"):
                if key not in prog:
                    problems.append(f"program_audit: missing {key!r}")
            for i, st in enumerate(prog.get("states") or ()):
                where = f"program_audit.states[{i}]"
                if not isinstance(st, dict) or "programs" not in st \
                        or "gate_state" not in st:
                    problems.append(
                        f"{where}: missing gate_state/programs"
                    )
                    continue
                for j, rec in enumerate(st["programs"]):
                    if not isinstance(rec, dict) or not {
                        "name", "ok", "problems", "device_put",
                        "callbacks",
                    } <= set(rec):
                        problems.append(
                            f"{where}.programs[{j}]: missing "
                            "name/ok/problems/device_put/callbacks"
                        )
    checks = doc.get("checks")
    if not isinstance(checks, dict):
        problems.append("checks: not a dict")
    else:
        for key in ("ast_clean", "program_ok", "clean"):
            if key not in checks:
                problems.append(f"checks: missing {key!r}")
    return problems


#: schema tag of the per-device-generation winner bank file
#: (tmr_tpu/autotune_live.py): one validated document holding live- and
#: offline-elected formulation winners keyed
#: ``device_kind|knob|geometry``, every entry stamped with the sweep
#: revision it was measured under (autotune's ``_SWEEP_REV`` staleness
#: discipline — a stale entry falls back to the offline cache instead of
#: electing). Written only via atomicio.atomic_write.
WINNER_BANK_SCHEMA = "winner_bank/v1"

#: entry provenance vocabulary: "offline" = seeded from the autotune
#: cache's sweep winners; "live" = elected (or restored by a demotion)
#: from shadow-measured production traffic.
WINNER_BANK_SOURCES = ("offline", "live")


def validate_winner_bank(doc: dict) -> List[str]:
    """Structural check of a winner_bank/v1 document; returns a list of
    problems (empty == valid). Dependency-free like the other
    validators — semantic checks that need autotune's variant sets
    (winner membership, key/entry agreement) live in
    ``autotune_live.load_bank``, which also degrades best-effort."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != WINNER_BANK_SCHEMA:
        problems.append(
            f"schema != {WINNER_BANK_SCHEMA}: {doc.get('schema')!r}"
        )
    if not isinstance(doc.get("sweep_rev"), str) or not doc.get("sweep_rev"):
        problems.append("sweep_rev: not a non-empty string")
    if not isinstance(doc.get("ts"), (int, float)) \
            or isinstance(doc.get("ts"), bool):
        problems.append("ts: not a number")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        problems.append("entries: not a dict")
        entries = {}
    for key, entry in entries.items():
        where = f"entries[{key!r}]"
        if not isinstance(entry, dict):
            problems.append(f"{where}: not a dict")
            continue
        for field in ("device_kind", "knob", "geometry", "winner",
                      "sweep_rev"):
            if not isinstance(entry.get(field), str):
                problems.append(f"{where}.{field}: not a string")
        if entry.get("source") not in WINNER_BANK_SOURCES:
            problems.append(
                f"{where}.source: bad source {entry.get('source')!r}"
            )
        if not isinstance(entry.get("wins"), int) \
                or isinstance(entry.get("wins"), bool):
            problems.append(f"{where}.wins: not an int")
        if not isinstance(entry.get("ts"), (int, float)) \
                or isinstance(entry.get("ts"), bool):
            problems.append(f"{where}.ts: not a number")
        per_item = entry.get("device_s_per_item")
        if per_item is not None and (
            not isinstance(per_item, dict) or not all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in per_item.values()
            )
        ):
            problems.append(
                f"{where}.device_s_per_item: not a dict of numbers"
            )
    return problems


#: schema tag of the continuous-autotune probe/report document
#: (scripts/live_tune_probe.py over tmr_tpu/autotune_live.py): the
#: tuner's replayable decision log (every shadow measurement, oracle
#: refusal, promotion, and demotion with cause), its shadow-budget
#: accounting, and the probe's fail-closed checks — disabled-mode
#: bitwise identity, <1% shadow fraction, promotion speedup with zero
#: hot-path cold compiles, anomaly demotion, replay consistency.
#: ``bench_trend.py --live-tune`` rc-gates on ``checks``.
LIVE_TUNE_REPORT_SCHEMA = "live_tune_report/v1"

#: closed decision-event vocabulary of the replayable log: "shadow" =
#: one symmetric incumbent-vs-candidate measurement; "refusal" = the
#: oracle rejected the candidate's result (arm disqualified);
#: "promote" / "demote" = an election changed the serving formulation.
LIVE_TUNE_EVENTS = ("shadow", "refusal", "promote", "demote")


def validate_live_tune_report(doc: dict) -> List[str]:
    """Structural check of a live_tune_report/v1 document; returns a
    list of problems (empty == valid). An error record
    ({"schema": ..., "error": str}) is contractually valid (the probe's
    wedge path). Dependency-free like the other validators."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a dict: {type(doc).__name__}"]
    if doc.get("schema") != LIVE_TUNE_REPORT_SCHEMA:
        problems.append(
            f"schema != {LIVE_TUNE_REPORT_SCHEMA}: {doc.get('schema')!r}"
        )
    if "error" in doc:
        if not isinstance(doc["error"], str) or not doc["error"]:
            problems.append("error: not a non-empty string")
        return problems
    if not isinstance(doc.get("device_kind"), str) \
            or not doc.get("device_kind"):
        problems.append("device_kind: not a non-empty string")
    tuner = doc.get("tuner")
    if not isinstance(tuner, dict):
        problems.append("tuner: not a dict")
    else:
        for field in ("knob", "incumbent"):
            if not isinstance(tuner.get(field), str) \
                    or not tuner.get(field):
                problems.append(f"tuner.{field}: not a non-empty string")
        counters = tuner.get("counters")
        if not isinstance(counters, dict) or not all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in counters.values()
        ):
            problems.append("tuner.counters: not a dict of numbers")
        decisions = tuner.get("decisions")
        if not isinstance(decisions, list):
            problems.append("tuner.decisions: not a list")
        else:
            for i, rec in enumerate(decisions):
                where = f"tuner.decisions[{i}]"
                if not isinstance(rec, dict):
                    problems.append(f"{where}: not a dict")
                    continue
                if rec.get("event") not in LIVE_TUNE_EVENTS:
                    problems.append(
                        f"{where}.event: bad event {rec.get('event')!r}"
                    )
                for field in ("knob", "arm"):
                    if not isinstance(rec.get(field), str):
                        problems.append(f"{where}.{field}: not a string")
                if not isinstance(rec.get("ts"), (int, float)) \
                        or isinstance(rec.get("ts"), bool):
                    problems.append(f"{where}.ts: not a number")
                if rec.get("event") == "demote" and (
                    not isinstance(rec.get("cause"), str)
                    or not rec.get("cause")
                ):
                    problems.append(
                        f"{where}.cause: demote without a cause"
                    )
    summary = doc.get("summary")
    if not isinstance(summary, dict):
        problems.append("summary: not a dict")
    checks = doc.get("checks")
    if not isinstance(checks, dict) or not checks or not all(
        isinstance(v, bool) for v in checks.values()
    ):
        problems.append("checks: not a non-empty dict of booleans")
    return problems


#: registry bound: the attention gates are lru_cached (one record per
#: config) but pallas_xcorr_ok's pre-cache refusals (kill-switch /
#: backend / shape) record on EVERY call — a long-lived process that
#: never drains must not grow without bound, so the oldest records roll
#: off past this many. Consumers drain far below it in practice.
_MAX_GATE_REFUSALS = 256

_GATE_REFUSALS: List[dict] = []


class FormulationFallbackWarning(UserWarning):
    """An explicitly requested kernel formulation fell back at trace time.

    ``env_var`` names the knob whose request was refused (e.g.
    "TMR_GLOBAL_ATTN", "TMR_WIN_ATTN", "TMR_XCORR_IMPL",
    "TMR_XCORR_IMPL_SMALL")."""

    def __init__(self, env_var: str, message: str):
        super().__init__(message)
        self.env_var = env_var


def record_gate_refusal(
    gate: str,
    cause: str,
    message: str = "",
    exception: Optional[str] = None,
    config: Optional[Dict[str, object]] = None,
) -> dict:
    """Append one structured refusal record and return it.

    ``cause`` is a small closed vocabulary so consumers can branch without
    parsing messages: "kill-switch" (env force-disable), "backend" (wrong
    default backend), "forward-mismatch" / "grad-mismatch" (numerics
    disagreed with the oracle beyond tolerance), "exception" (the check
    raised — ``exception`` then carries the class name and ``message`` the
    stringified error, Mosaic lowering failures included). ``config`` is
    the gate's cache key made explicit: geometry plus whatever the verdict
    is scoped to (tile sizes, window group, scores dtype).

    Note the gates are lru_cached: a refusal records only when the check
    actually RUNS (cache miss). Diagnostics consumers that need causes for
    a previously cached False must ``cache_clear()`` first — exactly what
    scripts/gate_probe.py does.
    """
    rec: dict = {
        "schema": GATE_PROBE_SCHEMA,
        "gate": gate,
        "cause": cause,
        "message": message,
        "exception": exception,
        "config": dict(config or {}),
    }
    try:  # backend identity is best-effort: never let diagnostics raise
        import jax

        rec["backend"] = jax.default_backend()
        rec["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        rec["backend"] = None
        rec["device_kind"] = None
    _GATE_REFUSALS.append(rec)
    if len(_GATE_REFUSALS) > _MAX_GATE_REFUSALS:
        del _GATE_REFUSALS[:-_MAX_GATE_REFUSALS]
    return rec


def gate_refused(
    gate: str,
    reason: str,
    cause: str,
    config: Optional[Dict[str, object]] = None,
    exception: Optional[str] = None,
) -> bool:
    """record_gate_refusal + the TMR_GATE_DEBUG stderr line, returning
    False so gate checks can ``return gate_refused(...)`` — the one
    definition of the refuse-and-say-why move every oracle gate makes
    (fused_heads / quant / postprocess use it; the older attention and
    xcorr gates predate it)."""
    import os

    record_gate_refusal(gate, cause, message=reason, exception=exception,
                        config=config)
    if os.environ.get("TMR_GATE_DEBUG"):
        import sys

        print(f"[gate] {gate}: refused — {reason}", file=sys.stderr)
    return False


def gate_refusals() -> List[dict]:
    """Snapshot of the recorded refusals (oldest first), not cleared."""
    return list(_GATE_REFUSALS)


def drain_gate_refusals() -> List[dict]:
    """Return all recorded refusals and clear the registry — the harness
    protocol: drain before a measurement to discard stale records, drain
    after to attribute fresh ones to that measurement."""
    out = list(_GATE_REFUSALS)
    _GATE_REFUSALS.clear()
    return out
