"""Shared diagnostic warning types + the structured gate-refusal registry
(dependency-free at import time — importable from any layer: ops, models,
utils).

``FormulationFallbackWarning`` is the structural contract between the
trace-time formulation dispatchers (models/vit.py attention, ops/xcorr.py
correlation) and the measurement harnesses (utils/autotune.py sweeps,
scripts/profile_breakdown.py): when an EXPLICITLY requested formulation is
refused by its gate/dtype precondition and a fallback traces instead, the
dispatcher warns with this category carrying ``env_var`` — so harnesses can
detect by category + attribute (not message substrings) that a timing
recorded under the requested label actually measured the fallback.

The gate-refusal REGISTRY is the machine-readable side of the same story
(round-5 verdict #1: on the live TPU every require_tpu kernel fell back
and the gates swallowed WHY). Every refusal inside the compiled
self-checks (ops/flash_attn._self_check and the gates built on it —
pallas_global_ok, pallas_fused_ok, pallas_window_ok, flash_attention_ok,
…) records a ``gate_probe.json``-schema cause here: refusal category,
exception class + message when one was swallowed, the tile/geometry
config the verdict keys on, and the device kind. Consumers drain it:
scripts/gate_probe.py --json emits the causes next to each probe, and the
autotune sweeps attach them to fallback-labeled rows so a "(fallback)"
timing always travels with the reason the requested kernel refused.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: schema tag stamped on every refusal record and on the gate_probe.py
#: --json document — bump when the record shape changes incompatibly
GATE_PROBE_SCHEMA = "gate_probe/v1"

#: registry bound: the attention gates are lru_cached (one record per
#: config) but pallas_xcorr_ok's pre-cache refusals (kill-switch /
#: backend / shape) record on EVERY call — a long-lived process that
#: never drains must not grow without bound, so the oldest records roll
#: off past this many. Consumers drain far below it in practice.
_MAX_GATE_REFUSALS = 256

_GATE_REFUSALS: List[dict] = []


class FormulationFallbackWarning(UserWarning):
    """An explicitly requested kernel formulation fell back at trace time.

    ``env_var`` names the knob whose request was refused (e.g.
    "TMR_GLOBAL_ATTN", "TMR_WIN_ATTN", "TMR_XCORR_IMPL",
    "TMR_XCORR_IMPL_SMALL")."""

    def __init__(self, env_var: str, message: str):
        super().__init__(message)
        self.env_var = env_var


def record_gate_refusal(
    gate: str,
    cause: str,
    message: str = "",
    exception: Optional[str] = None,
    config: Optional[Dict[str, object]] = None,
) -> dict:
    """Append one structured refusal record and return it.

    ``cause`` is a small closed vocabulary so consumers can branch without
    parsing messages: "kill-switch" (env force-disable), "backend" (wrong
    default backend), "forward-mismatch" / "grad-mismatch" (numerics
    disagreed with the oracle beyond tolerance), "exception" (the check
    raised — ``exception`` then carries the class name and ``message`` the
    stringified error, Mosaic lowering failures included). ``config`` is
    the gate's cache key made explicit: geometry plus whatever the verdict
    is scoped to (tile sizes, window group, scores dtype).

    Note the gates are lru_cached: a refusal records only when the check
    actually RUNS (cache miss). Diagnostics consumers that need causes for
    a previously cached False must ``cache_clear()`` first — exactly what
    scripts/gate_probe.py does.
    """
    rec: dict = {
        "schema": GATE_PROBE_SCHEMA,
        "gate": gate,
        "cause": cause,
        "message": message,
        "exception": exception,
        "config": dict(config or {}),
    }
    try:  # backend identity is best-effort: never let diagnostics raise
        import jax

        rec["backend"] = jax.default_backend()
        rec["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        rec["backend"] = None
        rec["device_kind"] = None
    _GATE_REFUSALS.append(rec)
    if len(_GATE_REFUSALS) > _MAX_GATE_REFUSALS:
        del _GATE_REFUSALS[:-_MAX_GATE_REFUSALS]
    return rec


def gate_refusals() -> List[dict]:
    """Snapshot of the recorded refusals (oldest first), not cleared."""
    return list(_GATE_REFUSALS)


def drain_gate_refusals() -> List[dict]:
    """Return all recorded refusals and clear the registry — the harness
    protocol: drain before a measurement to discard stale records, drain
    after to attribute fresh ones to that measurement."""
    out = list(_GATE_REFUSALS)
    _GATE_REFUSALS.clear()
    return out
