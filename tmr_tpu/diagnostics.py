"""Shared diagnostic warning types (dependency-free — importable from any
layer: ops, models, utils).

``FormulationFallbackWarning`` is the structural contract between the
trace-time formulation dispatchers (models/vit.py attention, ops/xcorr.py
correlation) and the measurement harnesses (utils/autotune.py sweeps,
scripts/profile_breakdown.py): when an EXPLICITLY requested formulation is
refused by its gate/dtype precondition and a fallback traces instead, the
dispatcher warns with this category carrying ``env_var`` — so harnesses can
detect by category + attribute (not message substrings) that a timing
recorded under the requested label actually measured the fallback.
"""

from __future__ import annotations


class FormulationFallbackWarning(UserWarning):
    """An explicitly requested kernel formulation fell back at trace time.

    ``env_var`` names the knob whose request was refused (e.g.
    "TMR_GLOBAL_ATTN", "TMR_WIN_ATTN", "TMR_XCORR_IMPL",
    "TMR_XCORR_IMPL_SMALL")."""

    def __init__(self, env_var: str, message: str):
        super().__init__(message)
        self.env_var = env_var
