"""Continuous in-production autotune (ROADMAP item 5): shadow-elected
formulation winners with per-device-generation winner banks.

The offline sweep (utils/autotune.py) can only re-elect winners at rare
hardware windows; this module re-elects them from LIVE traffic instead:

- **Winner banks** — one validated ``winner_bank/v1`` file keyed by
  ``(device_kind, knob, geometry)`` with each entry stamped by the sweep
  revision it was measured under (the offline ``_variants_sig`` /
  ``_SWEEP_REV`` staleness discipline), so v5e / v6e / CPU each carry
  their OWN elections and a harness revision bump makes every
  pre-revision entry stale (falls back to the offline cache) rather than
  electable. Writes go through ``atomicio.atomic_write`` — a promotion
  racing an offline sweep sees old or new, never a torn file.

- **Shadow measurement** — :class:`LiveTuner` samples a fraction of
  served batches (``TMR_LIVE_TUNE_SAMPLE``), re-executes each sample
  through the incumbent AND one candidate formulation OFF the critical
  path (a dedicated daemon thread; the serve pipeline only enqueues),
  under a device-seconds budget (``TMR_LIVE_TUNE_BUDGET``). A
  candidate's result must pass the oracle check against the incumbent
  before its timing counts — a refusal disqualifies the arm and is a
  recorded decision, never a silent drop.

- **Promotion / demotion** — the offline decisive-win policy
  (``_decisive_pick``: >10% win, ``win_ratio`` 0.9) applied per sample:
  ``TMR_LIVE_TUNE_WINS`` CONSECUTIVE decisive wins promote the
  candidate (bank entry hot-swapped, affected ``Predictor._compiled``
  keys invalidated — no restart); any ``HealthWatch`` /
  ``FleetHealthWatch`` demote-kind anomaly (:data:`DEMOTE_ANOMALIES`)
  or oracle refusal rolls back to the incumbent with the cause
  recorded. Every decision lands in a replayable log
  (:func:`replay_decisions` re-derives the same elections from the
  recorded shadow measurements).

- **Fleet-wide** — workers count decisive wins/refusals into their
  engine metrics registry (``live_tune.win.<knob>=<arm>``); the beats
  fold them coordinator-side (``state()["fleet_metrics"]``), where
  ``ServeFleet.live_tune_pass`` aggregates across workers and pushes
  the election back over the lease protocol's beat replies so the
  fleet converges on one winner per device generation.

Everything is OFF by default: ``TMR_LIVE_TUNE=0`` (unset) keeps serving
bitwise-identical — the engine holds ``_tuner = None`` and pays one
``is None`` check per batch; scripts/live_tune_probe.py pins it.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tmr_tpu.diagnostics import WINNER_BANK_SCHEMA, validate_winner_bank

#: anomaly kinds that demote a live promotion (the HealthWatch /
#: FleetHealthWatch vocabulary subset that reads "the formulation made
#: things worse"): single-engine MFU/latency regressions plus their
#: fleet-wide counterparts. Closed — a new demote trigger is a
#: deliberate addition here, not an incidental anomaly rename.
DEMOTE_ANOMALIES = (
    "mfu_drop",
    "fleet_mfu_drop",
    "latency_regression",
    "worker_outlier_latency",
)

#: default winner-bank location, next to the offline autotune cache
BANK_PATH = os.path.join(
    os.path.expanduser("~"), ".cache", "tmr_tpu", "winner_bank.json"
)

#: ``Predictor._compiled`` program kinds each live-tunable knob can
#: change: None = every program embeds the formulation (backbone attn,
#: quant numerics), a tuple = only those kinds re-trace. The
#: promotion's invalidation scope — too narrow would serve a stale
#: formulation, too wide only costs recompiles.
KNOB_PROGRAM_KINDS: Dict[str, Optional[Tuple[str, ...]]] = {
    "TMR_WIN_ATTN": None,
    "TMR_GLOBAL_ATTN": None,
    "TMR_XCORR_IMPL_SMALL": None,
    "TMR_QUANT": None,
    "TMR_QUANT_STORAGE": None,
    "TMR_DECODER_IMPL": (
        "single", "multi", "multi_batched", "heads",
        "gallery", "gallery_heads",
    ),
}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def live_tune_enabled() -> bool:
    """The master switch (``TMR_LIVE_TUNE``): unset/0 = continuous
    autotune fully off — ``ServeEngine.attach_live_tuner`` refuses, no
    sampling, no bank writes, serving stays bitwise-identical."""
    return os.environ.get("TMR_LIVE_TUNE", "") not in ("", "0")


def default_sample() -> float:
    """Sampled fraction of served batches (``TMR_LIVE_TUNE_SAMPLE``).
    The default 0.002 keeps shadow work (incumbent + candidate per
    sample = 2x) well under 1% of steady-state device seconds."""
    return max(min(_env_float("TMR_LIVE_TUNE_SAMPLE", 0.002), 1.0), 0.0)


def default_budget_s() -> float:
    """Device-seconds token budget for shadow execution per tuner
    (``TMR_LIVE_TUNE_BUDGET``): once spent, sampling stops (recorded)
    until a promotion/demotion resets the ledger."""
    return max(_env_float("TMR_LIVE_TUNE_BUDGET", 2.0), 0.0)


def default_wins() -> int:
    """Consecutive decisive wins required to promote
    (``TMR_LIVE_TUNE_WINS``)."""
    return max(_env_int("TMR_LIVE_TUNE_WINS", 3), 1)


# ------------------------------------------------------------ winner bank
def bank_path() -> str:
    """Bank file location: ``TMR_LIVE_TUNE_BANK`` override, else
    ``~/.cache/tmr_tpu/winner_bank.json``."""
    return os.environ.get("TMR_LIVE_TUNE_BANK") or BANK_PATH


def bank_key(device_kind: str, knob: str, geometry: str) -> str:
    """The per-(device generation, program knob, geometry) bank key —
    one definition so writer, reader, and tests can never drift."""
    return f"{device_kind}|{knob}|{geometry}"


def _sweep_rev() -> str:
    from tmr_tpu.utils.autotune import _SWEEP_REV

    return _SWEEP_REV


def _winner_ok(knob: str, value: str) -> bool:
    """A bank winner must be a value the formulation gate ladder knows:
    for knobs with an offline variant set, membership in that set (a
    FALLBACK_SUFFIX-annotated label is never electable — same contract
    as the offline ``_electable`` filter); for other knobs any
    non-empty plain string."""
    from tmr_tpu.utils import autotune as _at

    if not isinstance(value, str) or not value or \
            value.endswith(_at.FALLBACK_SUFFIX):
        return False
    sets = {
        "TMR_XCORR_IMPL_SMALL": set(_at.XCORR_VARIANTS) | {"auto"},
        "TMR_WIN_ATTN": set(_at.WIN_ATTN_VARIANTS),
        "TMR_GLOBAL_ATTN": set(_at.GLOBAL_ATTN_VARIANTS) | {"auto"},
        "TMR_DECODER_IMPL": set(_at.DECODER_IMPL_VARIANTS) | {"auto"},
        "TMR_QUANT": set(_at.QUANT_VARIANTS) | {"auto"},
        "TMR_QUANT_STORAGE": set(_at.STORAGE_VARIANTS),
    }
    allowed = sets.get(knob)
    return True if allowed is None else value in allowed


def load_bank(path: Optional[str] = None,
              device_kind: Optional[str] = None) -> Dict[str, dict]:
    """Validated bank entries from disk: ``{bank key: entry}``.

    Best-effort all the way down (a foreign/hand-edited file degrades
    to "no bank", never a crash), with two hard isolation rules:

    - a ``device_kind`` filter returns ONLY that generation's entries —
      a v5e election can never leak into a v6e (or CPU) process;
    - an entry whose ``sweep_rev`` predates the current harness
      revision is dropped (stale — the consumer falls back to the
      offline cache), exactly the offline ``_variants_sig`` staleness
      discipline.
    """
    path = path or bank_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if validate_winner_bank(doc):
        return {}
    rev = _sweep_rev()
    out: Dict[str, dict] = {}
    for key, entry in doc["entries"].items():
        if entry.get("sweep_rev") != rev:
            continue  # stale harness revision: never electable
        if device_kind is not None and \
                entry.get("device_kind") != device_kind:
            continue
        if key != bank_key(entry.get("device_kind", ""),
                           entry.get("knob", ""),
                           str(entry.get("geometry", ""))):
            continue  # key/entry mismatch: a hand-edit, drop it
        if not _winner_ok(entry.get("knob", ""),
                          entry.get("winner", "")):
            continue
        out[key] = dict(entry)
    return out


def store_bank(entries: Dict[str, dict],
               path: Optional[str] = None) -> bool:
    """Atomically persist the full entry map as one ``winner_bank/v1``
    document. Best-effort like every autotune cache write (the elected
    winner is already live in-process; the bank is the cross-process
    memory)."""
    from tmr_tpu.utils.atomicio import atomic_write

    path = path or bank_path()
    doc = {
        "schema": WINNER_BANK_SCHEMA,
        "sweep_rev": _sweep_rev(),
        "ts": time.time(),
        "entries": entries,
    }

    def _write(f):
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")

    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write(path, _write)
    except OSError:
        return False
    return True


def make_entry(device_kind: str, knob: str, geometry: str, winner: str,
               *, source: str, wins: int = 0,
               device_s_per_item: Optional[Dict[str, float]] = None
               ) -> dict:
    """One bank entry in the validated shape."""
    entry = {
        "device_kind": str(device_kind),
        "knob": str(knob),
        "geometry": str(geometry),
        "winner": str(winner),
        "sweep_rev": _sweep_rev(),
        "source": str(source),
        "wins": int(wins),
        "ts": time.time(),
    }
    if device_s_per_item:
        entry["device_s_per_item"] = {
            k: float(v) for k, v in device_s_per_item.items()
        }
    return entry


def seed_bank_from_cache(device_kind: str,
                         path: Optional[str] = None) -> Dict[str, dict]:
    """Seed bank entries for one device generation from the offline
    autotune cache: every non-stale formulation winner the offline
    sweep recorded for this generation becomes an ``offline``-source
    entry (geometry = the cache key's shape suffix). Entries already in
    the bank for the same key are NOT overwritten — a live election
    always outranks its own seed. Returns the merged entry map (also
    persisted when anything new landed)."""
    from tmr_tpu.utils import autotune as _at

    bank = load_bank(path)
    added = False
    prefix = f"{device_kind}|"
    for cache_key, knobs in _at._cache_load().items():
        if not cache_key.startswith(prefix):
            continue
        geometry = cache_key[len(prefix):]
        for knob in _at._VERSIONED_KNOBS:
            winner = knobs.get(knob)
            if winner is None or not _winner_ok(knob, winner):
                continue
            if knobs.get(f"_variants_{knob}") != _at._variants_sig(knob):
                continue  # stale offline winner: not seedable
            key = bank_key(device_kind, knob, geometry)
            if key in bank:
                continue
            bank[key] = make_entry(device_kind, knob, geometry, winner,
                                   source="offline")
            added = True
    if added:
        store_bank(bank, path)
    return bank


def device_generation() -> str:
    """The winner bank's device-generation key for THIS process:
    devtime's peak table identity (``TPU v5e`` / ``TPU v6e`` / ...)
    when resolvable, else the backend name — CPU processes bank under
    ``cpu``, never under a TPU generation."""
    try:
        from tmr_tpu.obs import devtime

        peak = devtime.platform_peak()
        kind = peak.get("device_kind")
        if kind:
            return str(kind)
    except Exception:
        pass
    try:
        import jax

        return str(jax.default_backend())
    except Exception:
        return "cpu"


# -------------------------------------------------------- compiled swap
def apply_winner(predictor: Any, knob: str, value: str) -> int:
    """Hot-swap one formulation winner into the running process: export
    the env knob (programs read it at trace time) and invalidate the
    affected ``Predictor._compiled`` entries so the next call
    re-traces under the new formulation — no restart. Returns the
    number of dropped programs (0 for predictors without the hook,
    e.g. the numpy fleet stub)."""
    os.environ[knob] = str(value)
    inv = getattr(predictor, "invalidate_compiled", None)
    if not callable(inv):
        return 0
    return int(inv(KNOB_PROGRAM_KINDS.get(knob)))


def default_oracle(base: Any, cand: Any) -> bool:
    """Result agreement check used when the caller supplies no oracle:
    detection dicts must match exactly (the serve exactness contract —
    a candidate formulation that changes results is REFUSED regardless
    of its timing; knobs with documented ULP exceptions supply their
    own tolerance oracle)."""
    import numpy as np

    if isinstance(base, dict) and isinstance(cand, dict):
        keys = [k for k in base if k != "degrade_steps"]
        if any(k not in cand for k in keys):
            return False
        return all(
            np.array_equal(np.asarray(base[k]), np.asarray(cand[k]))
            for k in keys
        )
    return bool(np.array_equal(np.asarray(base), np.asarray(cand)))


# ------------------------------------------------------------- the tuner
class LiveTuner:
    """Shadow-measuring election loop for ONE formulation knob.

    ``runner(arm, payload)`` executes the sampled payload through the
    formulation ``arm`` and returns ``(result, device_s)`` — the
    engine-side runner re-executes the batch through the candidate
    program (devtime-measured); probes inject deterministic stubs.
    ``payload`` is opaque to the tuner (the engine passes the batch's
    host inputs).

    The serve pipeline calls :meth:`offer` per completed batch — a
    sampling decision plus a bounded non-blocking enqueue; the shadow
    execution itself runs on this tuner's daemon thread, off the
    critical path, under the device-seconds budget.
    """

    def __init__(self, knob: str, arms: Sequence[str], incumbent: str,
                 *, runner: Callable[[str, Any], Tuple[Any, float]],
                 oracle: Optional[Callable[[Any, Any], bool]] = None,
                 device_kind: Optional[str] = None, geometry: str = "",
                 sample: Optional[float] = None,
                 budget_s: Optional[float] = None,
                 wins_needed: Optional[int] = None,
                 win_ratio: float = 0.9,
                 bank_file: Optional[str] = None,
                 apply_fn: Optional[Callable[[str, str], Any]] = None,
                 metrics: Optional[Any] = None,
                 queue_depth: int = 4):
        self.knob = str(knob)
        self.incumbent = str(incumbent)
        self.arms = [str(a) for a in arms if str(a) != self.incumbent]
        self._runner = runner
        self._oracle = oracle or default_oracle
        self.device_kind = device_kind or device_generation()
        self.geometry = str(geometry)
        self.sample = default_sample() if sample is None \
            else max(min(float(sample), 1.0), 0.0)
        self.budget_s = default_budget_s() if budget_s is None \
            else float(budget_s)
        self.wins_needed = default_wins() if wins_needed is None \
            else max(int(wins_needed), 1)
        self.win_ratio = float(win_ratio)
        self.bank_file = bank_file
        self._apply_fn = apply_fn
        self._metrics = metrics
        self._stride = int(round(1.0 / self.sample)) if self.sample > 0 \
            else 0
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue(
            maxsize=max(int(queue_depth), 1)
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # election state (all under self._lock)
        self._arm_i = 0
        self._wins: Dict[str, int] = {}
        self._disqualified: set = set()
        self._prev_incumbent: Optional[str] = None
        self._promoted: Optional[str] = None
        self.decisions: List[dict] = []
        self._counters: Dict[str, float] = {
            "offers": 0, "sampled": 0, "shadow_runs": 0, "dropped": 0,
            "refusals": 0, "promotions": 0, "demotions": 0,
            "budget_stops": 0, "items": 0,
            "shadow_device_s": 0.0, "incumbent_device_s": 0.0,
            "incumbent_items": 0,
        }

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "LiveTuner":
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._shadow_loop,
                    name=f"live-tune-{self.knob}", daemon=True,
                )
                self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            self._q.put(None)
            t.join(timeout=timeout)

    def drain(self, timeout: float = 5.0) -> None:
        """Block until every enqueued sample has been shadow-measured
        (probe/test synchronization — production never calls it)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.empty() and not self._busy():
                return
            time.sleep(0.005)

    def _busy(self) -> bool:
        with self._lock:
            return bool(self._counters.get("_inflight"))

    # --------------------------------------------------------------- offers
    def offer(self, payload: Any, base_result: Any,
              items: int = 1) -> bool:
        """One completed serve batch: count it, decide sampling
        (deterministic stride — every ``1/sample``-th offer), and
        enqueue for shadow measurement when within budget. Never
        blocks; a full queue drops the sample (counted)."""
        with self._lock:
            self._counters["offers"] += 1
            self._counters["items"] += max(int(items), 1)
            if self._stride <= 0 or self._stop.is_set():
                return False
            if int(self._counters["offers"] - 1) % self._stride:
                return False
            if self._counters["shadow_device_s"] >= self.budget_s:
                self._counters["budget_stops"] += 1
                return False
            self._counters["sampled"] += 1
        try:
            self._q.put_nowait((payload, base_result,
                                max(int(items), 1)))
        except queue.Full:
            with self._lock:
                self._counters["dropped"] += 1
            return False
        return True

    # --------------------------------------------------------------- shadow
    def _shadow_loop(self) -> None:
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            with self._lock:
                self._counters["_inflight"] = 1
            try:
                self._shadow_one(*item)
            except Exception:
                pass  # a shadow failure must never hurt serving
            finally:
                with self._lock:
                    self._counters.pop("_inflight", None)

    def _next_arm(self) -> Optional[str]:
        with self._lock:
            live = [a for a in self.arms
                    if a not in self._disqualified
                    and a != self.incumbent]
            if not live:
                return None
            arm = live[self._arm_i % len(live)]
            self._arm_i += 1
            return arm

    def _shadow_one(self, payload: Any, base_result: Any,
                    items: int) -> None:
        arm = self._next_arm()
        if arm is None:
            return
        # incumbent first: symmetric measurement (same runner, same
        # payload, same synchronous timing) — comparing a candidate's
        # blocking time against the pipeline's async dispatch time
        # would systematically flatter the pipeline
        base_out, base_s = self._runner(self.incumbent, payload)
        cand_out, cand_s = self._runner(arm, payload)
        with self._lock:
            self._counters["shadow_runs"] += 1
            self._counters["shadow_device_s"] += float(base_s) + \
                float(cand_s)
            self._counters["incumbent_device_s"] += float(base_s)
            self._counters["incumbent_items"] += items
        ok = False
        try:
            # the gate/oracle ladder: the candidate's RESULT must match
            # the incumbent's before its TIMING counts
            ok = bool(self._oracle(base_out, cand_out)) and (
                base_result is None or
                bool(self._oracle(base_result, base_out))
            )
        except Exception:
            ok = False
        per_item = max(items, 1)
        if not ok:
            self._refuse(arm, base_s / per_item, cand_s / per_item,
                         items)
            return
        win = cand_s < self.win_ratio * base_s
        with self._lock:
            if win:
                self._wins[arm] = self._wins.get(arm, 0) + 1
            else:
                # decisive wins are CONSECUTIVE: one non-win resets
                # the arm (the replayable policy — see
                # replay_decisions)
                self._wins[arm] = 0
            wins = self._wins[arm]
            self._record("shadow", arm, win=win, wins=wins,
                         base_s_per_item=base_s / per_item,
                         cand_s_per_item=cand_s / per_item,
                         items=items)
            decisive = win and wins >= self.wins_needed \
                and self._promoted is None
        if decisive:
            self.promote(arm)

    # ------------------------------------------------------------ decisions
    def _record(self, event: str, arm: str, **fields) -> None:
        """Append one decision (caller holds ``self._lock``)."""
        self.decisions.append({
            "event": event, "knob": self.knob, "arm": arm,
            "ts": time.time(), **fields,
        })

    def _count_metric(self, name: str) -> None:
        if self._metrics is not None:
            try:
                self._metrics.counter(name).inc()
            except Exception:
                pass

    def _refuse(self, arm: str, base_s: float, cand_s: float,
                items: int) -> None:
        with self._lock:
            self._counters["refusals"] += 1
            self._disqualified.add(arm)
            self._wins[arm] = 0
            self._record("refusal", arm, base_s_per_item=base_s,
                         cand_s_per_item=cand_s, items=items)
            demote = self._promoted == arm
        self._count_metric(f"live_tune.refusal.{self.knob}={arm}")
        if demote:
            self.demote("oracle_refusal")

    def promote(self, arm: str) -> None:
        """Hot-swap ``arm`` in as the serving formulation: bank entry
        written (atomic), affected compiled programs invalidated via
        ``apply_fn``, decision recorded."""
        with self._lock:
            if self._promoted is not None or arm == self.incumbent:
                return
            self._prev_incumbent = self.incumbent
            self._promoted = arm
            self.incumbent = arm
            self._counters["promotions"] += 1
            wins = self._wins.get(arm, 0)
            self._record("promote", arm, wins=wins,
                         previous=self._prev_incumbent)
        self._count_metric(f"live_tune.win.{self.knob}={arm}")
        self._write_bank(arm, source="live", wins=wins)
        if self._apply_fn is not None:
            try:
                self._apply_fn(self.knob, arm)
            except Exception:
                pass

    def demote(self, cause: str, evidence: Optional[dict] = None) -> None:
        """Roll back the live promotion to its incumbent, cause
        recorded. A no-op when nothing is promoted (anomalies unrelated
        to a live election must not thrash the bank)."""
        with self._lock:
            if self._promoted is None:
                return
            arm, self._promoted = self._promoted, None
            prev = self._prev_incumbent or arm
            self._prev_incumbent = None
            self.incumbent = prev
            self._disqualified.add(arm)
            self._wins[arm] = 0
            self._counters["demotions"] += 1
            rec_evidence = dict(evidence or {})
            self._record("demote", arm, cause=str(cause),
                         restored=prev, evidence=rec_evidence)
        self._count_metric(f"live_tune.demotion.{self.knob}={arm}")
        self._write_bank(prev, source="live", wins=0)
        if self._apply_fn is not None:
            try:
                self._apply_fn(self.knob, prev)
            except Exception:
                pass

    def observe_anomalies(self, records: Sequence[dict]) -> None:
        """HealthWatch/FleetHealthWatch listener hook: any demote-kind
        anomaly rolls a live promotion back (first one wins; the rest
        of the pass is moot once demoted)."""
        for rec in records or ():
            if not isinstance(rec, dict):
                continue
            kind = rec.get("anomaly")
            if kind in DEMOTE_ANOMALIES:
                self.demote(kind, evidence=rec.get("evidence") or {})
                return

    def _write_bank(self, winner: str, *, source: str,
                    wins: int) -> None:
        try:
            bank = load_bank(self.bank_file)
            key = bank_key(self.device_kind, self.knob, self.geometry)
            with self._lock:
                per_item = {}
                n = self._counters["incumbent_items"]
                if n:
                    per_item["incumbent"] = (
                        self._counters["incumbent_device_s"] / n
                    )
            bank[key] = make_entry(
                self.device_kind, self.knob, self.geometry, winner,
                source=source, wins=wins,
                device_s_per_item=per_item or None,
            )
            store_bank(bank, self.bank_file)
        except Exception:
            pass  # bank persistence is best-effort, elections are live

    # -------------------------------------------------------------- report
    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if not k.startswith("_")}

    def shadow_fraction(self) -> Optional[float]:
        """Shadow device seconds as a fraction of the ESTIMATED
        steady-state serve device seconds (mean incumbent per-item cost
        x every item served) — the <1% acceptance pin's measurement."""
        with self._lock:
            n = self._counters["incumbent_items"]
            items = self._counters["items"]
            shadow = self._counters["shadow_device_s"]
            if not n or not items:
                return None
            served_est = (self._counters["incumbent_device_s"] / n) \
                * items
            return shadow / served_est if served_est > 0 else None

    def report(self) -> dict:
        """The tuner's slice of a ``live_tune_report/v1`` document
        (the probe wraps it with its checks section)."""
        with self._lock:
            return {
                "knob": self.knob,
                "device_kind": self.device_kind,
                "geometry": self.geometry,
                "incumbent": self.incumbent,
                "promoted": self._promoted,
                "arms": list(self.arms),
                "disqualified": sorted(self._disqualified),
                "sample": self.sample,
                "budget_s": self.budget_s,
                "wins_needed": self.wins_needed,
                "win_ratio": self.win_ratio,
                "counters": {k: v for k, v in self._counters.items()
                             if not k.startswith("_")},
                "decisions": [dict(d) for d in self.decisions],
            }


def replay_decisions(decisions: Sequence[dict], *, wins_needed: int,
                     win_ratio: float = 0.9) -> List[Tuple[str, str]]:
    """Pure re-election over a recorded decision log: feed the shadow
    measurements (and the externally-triggered refusal/demote inputs)
    through the same consecutive-decisive-win policy and return the
    ``(event, arm)`` sequence it reaches. A log whose recorded
    promote/demote events match this replay is internally consistent —
    the election was a function of its measurements, not of a race."""
    wins: Dict[str, int] = {}
    disqualified: set = set()
    promoted: Optional[str] = None
    out: List[Tuple[str, str]] = []
    for rec in decisions or ():
        event, arm = rec.get("event"), rec.get("arm")
        if event == "refusal":
            disqualified.add(arm)
            wins[arm] = 0
            if promoted == arm:
                out.append(("demote", arm))
                promoted = None
            continue
        if event == "demote":
            # anomaly-triggered: an input to the policy, echoed —
            # but only legal against the live promotion
            if promoted == arm:
                out.append(("demote", arm))
                promoted = None
                disqualified.add(arm)
            continue
        if event != "shadow" or arm in disqualified:
            continue
        base = rec.get("base_s_per_item")
        cand = rec.get("cand_s_per_item")
        win = (cand < win_ratio * base) \
            if isinstance(base, (int, float)) and \
            isinstance(cand, (int, float)) else bool(rec.get("win"))
        wins[arm] = wins.get(arm, 0) + 1 if win else 0
        if win and wins[arm] >= wins_needed and promoted is None:
            out.append(("promote", arm))
            promoted = arm
    return out


def recorded_elections(decisions: Sequence[dict]
                       ) -> List[Tuple[str, str]]:
    """The promote/demote events a decision log actually recorded, in
    order — what :func:`replay_decisions` must reproduce."""
    return [(rec["event"], rec.get("arm"))
            for rec in decisions or ()
            if rec.get("event") in ("promote", "demote")]
