"""Minimal COCO annotation index (replaces pycocotools.coco.COCO for the
read paths the reference uses: imgs, getImgIds, getAnnIds, loadAnns —
pycocotools is not installed in this image)."""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, List


class COCOIndex:
    def __init__(self, annotation_file: str):
        with open(annotation_file) as f:
            data = json.load(f)
        self.dataset = data
        self.imgs: Dict[object, dict] = {
            im["id"]: im for im in data.get("images", [])
        }
        self.anns: Dict[object, dict] = {
            a["id"]: a for a in data.get("annotations", [])
        }
        self._img_to_anns: Dict[object, List[dict]] = defaultdict(list)
        for a in data.get("annotations", []):
            self._img_to_anns[a["image_id"]].append(a)

    def get_img_ids(self) -> list:
        return list(self.imgs.keys())

    def get_ann_ids(self, img_ids) -> list:
        out = []
        for i in img_ids:
            out.extend(a["id"] for a in self._img_to_anns.get(i, []))
        return out

    def load_anns(self, ann_ids) -> list:
        return [self.anns[i] for i in ann_ids]

    def anns_for_image(self, img_id) -> list:
        return list(self._img_to_anns.get(img_id, []))
