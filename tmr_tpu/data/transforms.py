"""Image preprocessing (reference datamodules/transforms.py, sans
albumentations).

The reference's train/eval transform is deterministic: Resize(S, S)
(cv2 INTER_LINEAR under albumentations) + ImageNet Normalize + CHW tensor
(transforms.py:42-50). The "large" variant is the same at 1536
(:61-69). We keep cv2 INTER_LINEAR for pixel parity and emit NHWC float32
(TPU layout). Resize semantics define the two static shape buckets
(1024 / 1536) that replace the reference's dynamic <25px escape hatch
branch at the model level.
"""

from __future__ import annotations

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize_image(image: np.ndarray) -> np.ndarray:
    """uint8/float HWC RGB -> ImageNet-normalized float32 HWC
    (albumentations A.Normalize: x/255 then (x - mean) / std)."""
    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    if np.issubdtype(img.dtype, np.integer):
        img = img.astype(np.float32) / 255.0
    else:
        # float input is taken as already [0, 1]; dtype (not content) decides
        img = img.astype(np.float32)
    return (img - IMAGENET_MEAN) / IMAGENET_STD


def resize_normalize(image: np.ndarray, size: int) -> np.ndarray:
    """Resize to (size, size) with cv2 INTER_LINEAR then normalize."""
    import cv2

    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    img = cv2.resize(img, (size, size), interpolation=cv2.INTER_LINEAR)
    return normalize_image(img)


SAM_PIXEL_MEAN = np.array([123.675, 116.28, 103.53], np.float32)
SAM_PIXEL_STD = np.array([58.395, 57.12, 57.375], np.float32)


def sam_longest_side_preprocess(
    image: np.ndarray, target: int = 1024
) -> np.ndarray:
    """The SAM-native preprocessing of extract_feature.py:50-64: resize the
    longest side to ``target`` (ResizeLongestSide semantics — round(scale *
    dim), cv2 INTER_LINEAR), normalize with SAM pixel mean/std (on 0-255
    values), zero-pad bottom/right to (target, target). HWC RGB in, float32
    (target, target, 3) NHWC-ready out."""
    import cv2

    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    h, w = img.shape[:2]
    scale = target / max(h, w)
    # ResizeLongestSide rounds half UP (int(x + 0.5)), not banker's-rounds
    nh, nw = int(h * scale + 0.5), int(w * scale + 0.5)
    img = cv2.resize(img, (nw, nh), interpolation=cv2.INTER_LINEAR)
    img = (img.astype(np.float32) - SAM_PIXEL_MEAN) / SAM_PIXEL_STD
    out = np.zeros((target, target, 3), np.float32)
    out[:nh, :nw] = img
    return out


def pick_image_size(orig_boxes: np.ndarray, base: int = 1024,
                    large: int = 1536, eval_mode: bool = False,
                    split: str = "train") -> int:
    """The small-object escape hatch (FSCD147.py:148-150, RPINE.py:123-125):
    eval/test images whose smallest GT box is < 25 px in BOTH dimensions run
    at 1536, else the base size."""
    if split != "test" or not eval_mode or len(orig_boxes) == 0:
        return base
    w = orig_boxes[:, 2] - orig_boxes[:, 0]
    h = orig_boxes[:, 3] - orig_boxes[:, 1]
    if w.min() < 25 and h.min() < 25:
        return large
    return base
