"""Image preprocessing (reference datamodules/transforms.py, sans
albumentations).

The reference's train/eval transform is deterministic: Resize(S, S)
(cv2 INTER_LINEAR under albumentations) + ImageNet Normalize + CHW tensor
(transforms.py:42-50). The "large" variant is the same at 1536
(:61-69). We keep cv2 INTER_LINEAR for pixel parity and emit NHWC float32
(TPU layout). Resize semantics define the two static shape buckets
(1024 / 1536) that replace the reference's dynamic <25px escape hatch
branch at the model level.
"""

from __future__ import annotations

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize_image(image: np.ndarray) -> np.ndarray:
    """uint8/float HWC RGB -> ImageNet-normalized float32 HWC
    (albumentations A.Normalize: x/255 then (x - mean) / std)."""
    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    if np.issubdtype(img.dtype, np.integer):
        img = img.astype(np.float32) / 255.0
    else:
        # float input is taken as already [0, 1]; dtype (not content) decides
        img = img.astype(np.float32)
    return (img - IMAGENET_MEAN) / IMAGENET_STD


def resize_normalize(image: np.ndarray, size: int) -> np.ndarray:
    """Resize to (size, size) with cv2 INTER_LINEAR then normalize."""
    import cv2

    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    img = cv2.resize(img, (size, size), interpolation=cv2.INTER_LINEAR)
    return normalize_image(img)


def gt_based_random_crop(
    image: np.ndarray,
    bboxes: np.ndarray,
    rng: np.random.Generator,
    keep_all_boxes: bool = False,
    labels: np.ndarray = None,
):
    """GT-anchored random crop (reference datamodules/transforms.py:10-35,
    the unused ``GTBasedRandomCrop`` augmentation, rebuilt without
    albumentations): pick a random GT box, grow a crop window from it by
    random amounts toward the image borders, crop, and re-normalize the
    boxes to the crop (dropping boxes whose center falls outside unless
    ``keep_all_boxes``).

    image: (H, W, C); bboxes: (N, 4) normalized xyxy; ``labels`` (N,)
    optional — when given, the anchor is sampled only from label-0 boxes
    (the reference restricts to label column == 0, transforms.py:23).
    Returns (cropped image, adjusted normalized boxes (M, 4), kept-index
    array).
    """
    if len(bboxes) == 0:
        raise ValueError("len(bboxes) must be > 0")
    h, w = image.shape[:2]
    candidates = np.arange(len(bboxes))
    if labels is not None:
        candidates = np.nonzero(np.asarray(labels) == 0)[0]
        if len(candidates) == 0:
            raise ValueError("no label-0 boxes to anchor the crop on")
    anchor = candidates[rng.integers(len(candidates))]
    x, y, x2, y2 = np.asarray(bboxes, np.float64)[anchor]

    bx = x * rng.random()
    by = y * rng.random()
    bx2 = x2 + (1 - x2) * rng.random()
    by2 = y2 + (1 - y2) * rng.random()

    px, py = int(bx * w), int(by * h)
    px2, py2 = max(int(bx2 * w), px + 1), max(int(by2 * h), py + 1)
    crop = image[py:py2, px:px2]
    cw, ch = px2 - px, py2 - py

    out_boxes, kept = [], []
    for i, (a, b, c, d) in enumerate(np.asarray(bboxes, np.float64)):
        nx1 = (a * w - px) / cw
        ny1 = (b * h - py) / ch
        nx2 = (c * w - px) / cw
        ny2 = (d * h - py) / ch
        cx, cy = (nx1 + nx2) / 2, (ny1 + ny2) / 2
        if not keep_all_boxes and not (0 <= cx <= 1 and 0 <= cy <= 1):
            continue
        out_boxes.append([np.clip(nx1, 0, 1), np.clip(ny1, 0, 1),
                          np.clip(nx2, 0, 1), np.clip(ny2, 0, 1)])
        kept.append(i)
    return crop, np.asarray(out_boxes, np.float32).reshape(-1, 4), np.asarray(
        kept, np.int64
    )


SAM_PIXEL_MEAN = np.array([123.675, 116.28, 103.53], np.float32)
SAM_PIXEL_STD = np.array([58.395, 57.12, 57.375], np.float32)


def sam_longest_side_preprocess(
    image: np.ndarray, target: int = 1024
) -> np.ndarray:
    """The SAM-native preprocessing of extract_feature.py:50-64: resize the
    longest side to ``target`` (ResizeLongestSide semantics — round(scale *
    dim), cv2 INTER_LINEAR), normalize with SAM pixel mean/std (on 0-255
    values), zero-pad bottom/right to (target, target). HWC RGB in, float32
    (target, target, 3) NHWC-ready out."""
    import cv2

    img = np.asarray(image)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.shape[-1] == 4:
        img = img[..., :3]
    h, w = img.shape[:2]
    scale = target / max(h, w)
    # ResizeLongestSide rounds half UP (int(x + 0.5)), not banker's-rounds
    nh, nw = int(h * scale + 0.5), int(w * scale + 0.5)
    img = cv2.resize(img, (nw, nh), interpolation=cv2.INTER_LINEAR)
    img = (img.astype(np.float32) - SAM_PIXEL_MEAN) / SAM_PIXEL_STD
    out = np.zeros((target, target, 3), np.float32)
    out[:nh, :nw] = img
    return out


def pick_image_size(orig_boxes: np.ndarray, base: int = 1024,
                    large: int = 1536, eval_mode: bool = False,
                    split: str = "train") -> int:
    """The small-object escape hatch (FSCD147.py:148-150, RPINE.py:123-125):
    eval/test images whose smallest GT box is < 25 px in BOTH dimensions run
    at 1536, else the base size."""
    if split != "test" or not eval_mode or len(orig_boxes) == 0:
        return base
    w = orig_boxes[:, 2] - orig_boxes[:, 0]
    h = orig_boxes[:, 3] - orig_boxes[:, 1]
    if w.min() < 25 and h.min() < 25:
        return large
    return base
