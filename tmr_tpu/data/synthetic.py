"""Synthetic FSCD-147-layout fixture: try the full pipeline with no data.

Writes a dataset in the exact on-disk layout the FSCD-147 reader expects
(reference datamodules/datasets/FSCD147.py: ``images_384_VarV2/`` +
``annotation_FSC147_384.json`` + ``Train_Test_Val_FSC_147.json`` +
``instances_{split}.json``): images with bright square "objects" planted on
a dark background, every object annotated as GT and the first two as
exemplars. Training on it converges to ~perfect AP in minutes on CPU
(tests/test_trainer_e2e.py uses the same generator as its convergence
regression), which makes it the quickstart path and a smoke fixture for
real-hardware runs.

CLI:  python -m tmr_tpu.data.synthetic --out /tmp/fsc [--n_train 16]
      [--n_val 4] [--image_size 128] [--square 28] [--seed 0]

NOTE on object size: with ``--eval``, the test split applies the
reference's small-object escalation (< 25 px objects run at the 1536
bucket, transforms.pick_image_size) — quickstart objects default to
28 px so a model trained at the fixture's own resolution evaluates at
that same resolution.
"""

from __future__ import annotations

import json
import os

import numpy as np


def write_synthetic_fscd147(
    root: str,
    n_train: int = 4,
    n_val: int = 2,
    image_size: int = 64,
    square: int = 10,
    seed: int = 0,
) -> list:
    """Write the fixture under ``root``; returns the image names."""
    from PIL import Image

    os.makedirs(f"{root}/annotations", exist_ok=True)
    os.makedirs(f"{root}/images_384_VarV2", exist_ok=True)
    rng = np.random.default_rng(seed)
    names = [f"im{i}.jpg" for i in range(n_train + n_val)]
    s, h = image_size, square // 2
    # two objects per image at fixed fractional positions (matches the
    # tests' planted-squares geometry at image_size=64)
    centers = [(int(0.25 * s), int(0.25 * s)), (int(0.6875 * s), int(0.625 * s))]
    annos, instances = {}, []
    aid = 1
    for i, n in enumerate(names):
        arr = (rng.uniform(0, 40, (s, s, 3))).astype(np.uint8)
        boxes = []
        for (cx, cy) in centers:
            arr[cy - h : cy + h, cx - h : cx + h] = 220
            boxes.append([cx - h, cy - h, square, square])
        Image.fromarray(arr).save(f"{root}/images_384_VarV2/{n}")
        annos[n] = {
            "box_examples_coordinates": [
                [[x, y], [x, y + bh], [x + bw, y + bh], [x + bw, y]]
                for (x, y, bw, bh) in boxes
            ]
        }
        for b in boxes:
            instances.append({"id": aid, "image_id": i, "bbox": b})
            aid += 1
    json.dump(
        annos, open(f"{root}/annotations/annotation_FSC147_384.json", "w")
    )
    json.dump(
        {
            "train": names[:n_train],
            "val": names[n_train:],
            "test": names[n_train:],
        },
        open(f"{root}/annotations/Train_Test_Val_FSC_147.json", "w"),
    )
    inst = {
        "images": [{"id": i, "file_name": n} for i, n in enumerate(names)],
        "annotations": instances,
    }
    for split in ("train", "val", "test"):
        json.dump(inst, open(f"{root}/annotations/instances_{split}.json", "w"))
    return names


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--n_train", type=int, default=16)
    p.add_argument("--n_val", type=int, default=4)
    p.add_argument("--image_size", type=int, default=128)
    p.add_argument("--square", type=int, default=28)
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args(argv)
    names = write_synthetic_fscd147(
        a.out, a.n_train, a.n_val, a.image_size, square=a.square, seed=a.seed
    )
    from tmr_tpu.utils.profiling import log_info

    log_info(f"wrote {len(names)} images to {a.out}")


if __name__ == "__main__":
    main()
