"""Dataset readers (reference datamodules/datasets/*).

Each item is a dict with the same information content as the reference's
__getitem__ returns (FSCD147.py:161-172, RPINE.py:136-147,
FSCD_LVIS.py:132+): NHWC normalized image, [0,1]-normalized boxes/exemplars,
and the metadata the eval pipeline logs. The <25px small-object escape hatch
picks the 1536 bucket at eval (see transforms.pick_image_size).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Optional

import numpy as np

from tmr_tpu.data.coco_index import COCOIndex
from tmr_tpu.data.transforms import pick_image_size, resize_normalize


def _load_json(path):
    with open(path) as f:
        return json.load(f)


class _Base:
    """Shared per-item pipeline: load -> normalize boxes -> size bucket ->
    resize+normalize -> item dict."""

    def __init__(self, image_size: int = 1024, max_exemplars: int = 1,
                 split: str = "train", eval_mode: bool = False):
        self.image_size = image_size
        self.max_exemplars = max_exemplars
        self.split = split
        self.eval_mode = eval_mode

    def _item(self, idx, img_name, img_url, image, bboxes, exemplars):
        img_w, img_h = image.size
        img_res = np.array([img_w, img_h, img_w, img_h], np.float32)
        scaled_boxes = bboxes / img_res[None, :]
        scaled_exemplars = exemplars / img_res[None, :]

        size = pick_image_size(
            bboxes, base=self.image_size, eval_mode=self.eval_mode,
            split=self.split,
        )
        arr = resize_normalize(np.array(image.convert("RGB")), size)
        return {
            "image": arr,  # (S, S, 3) float32 NHWC
            "boxes": scaled_boxes.astype(np.float32),
            "exemplars": scaled_exemplars.astype(np.float32),
            "img_name": img_name,
            "img_url": img_url,
            "img_id": idx,
            "img_size": np.array([img_w, img_h]),
            "orig_boxes": bboxes,
            "orig_exemplars": exemplars,
        }


class FSCD147Dataset(_Base):
    """FSC-147 exemplar json + COCO instance anns + split json
    (FSCD147.py:12-173)."""

    def __init__(self, root: str, split: str = "val", **kw):
        super().__init__(split=split, **kw)
        inst = {
            "train": "instances_train.json",
            "val": "instances_val.json",
            "test": "instances_test.json",
        }[split]
        self.im_dir = os.path.join(root, "images_384_VarV2")
        self.annotations = _load_json(
            os.path.join(root, "annotations", "annotation_FSC147_384.json")
        )
        self.data_split = _load_json(
            os.path.join(root, "annotations", "Train_Test_Val_FSC_147.json")
        )[split]
        self.instances = COCOIndex(os.path.join(root, "annotations", inst))
        self.name_to_id = {
            v["file_name"]: v["id"] for v in self.instances.imgs.values()
        }
        if self.max_exemplars > 3:
            raise ValueError("FSCD147 has maximum 3 exemplars per image")

    def __len__(self):
        return len(self.data_split)

    def __getitem__(self, idx):
        from PIL import Image

        img_name = self.data_split[idx]
        img_url = os.path.join(self.im_dir, img_name)
        image = Image.open(img_url)

        anns = self.instances.anns_for_image(self.name_to_id[img_name])
        bboxes = np.array(
            [
                [int(a["bbox"][0]), int(a["bbox"][1]),
                 int(a["bbox"][0] + a["bbox"][2]),
                 int(a["bbox"][1] + a["bbox"][3])]
                for a in anns
            ],
            np.float32,
        ).reshape(-1, 4)

        ex = []
        for box in self.annotations[img_name]["box_examples_coordinates"][
            : self.max_exemplars
        ]:
            # corner-list layout of FSCD147.py:85-90
            ex.append([box[0][0], box[0][1], box[2][0], box[2][1]])
        exemplars = np.array(ex, np.float32).reshape(-1, 4)
        return self._item(idx, img_name, img_url, image, bboxes, exemplars)


class FSCDLVISDataset(_Base):
    """FSCD-LVIS with seen/unseen split selection (FSCD_LVIS.py:12-183)."""

    def __init__(self, root: str, split: str = "train", unseen: bool = False,
                 **kw):
        super().__init__(split=split, **kw)
        pre = "unseen_" if unseen else ""
        part = "train" if split == "train" else "test"
        self.im_dir = os.path.join(root, "images")
        self.instances = COCOIndex(
            os.path.join(root, "annotations", f"{pre}instances_{part}.json")
        )
        counts = _load_json(
            os.path.join(root, "annotations", f"{pre}count_{part}.json")
        )
        # label_organizer (FSCD_LVIS.py:58-77): join images+annotations by id
        lib = {im["id"]: dict(im) for im in counts["images"]}
        for a in counts["annotations"]:
            lib[a["id"]].update(
                boxes=a["boxes"], points=a.get("points"), image_id=a["image_id"]
            )
        self.count_anno = {v["image_id"]: v for v in lib.values()
                           if "image_id" in v}
        self.image_ids = self.instances.get_img_ids()

    def __len__(self):
        return len(self.image_ids)

    def __getitem__(self, idx):
        from PIL import Image

        img_id = self.image_ids[idx]
        anno = self.count_anno[img_id]
        img_name = anno["file_name"]
        img_url = os.path.join(self.im_dir, img_name)
        image = Image.open(img_url)

        anns = self.instances.anns_for_image(img_id)
        bboxes = np.array(
            [
                [int(a["bbox"][0]), int(a["bbox"][1]),
                 int(a["bbox"][0] + a["bbox"][2]),
                 int(a["bbox"][1] + a["bbox"][3])]
                for a in anns
            ],
            np.float32,
        ).reshape(-1, 4)
        exemplars = np.array(
            [
                [int(x), int(y), int(x + w), int(y + h)]
                for x, y, w, h in anno["boxes"][: self.max_exemplars]
            ],
            np.float32,
        ).reshape(-1, 4)
        return self._item(idx, img_name, img_url, image, bboxes, exemplars)


class RPINEDataset(_Base):
    """RPINE: txt label files + exemplars.json, extension-sniffing image
    lookup (RPINE.py:11-148)."""

    def __init__(self, root: str, split: str = "test", **kw):
        super().__init__(split=split, **kw)
        self.image_path = os.path.join(root, "images")
        self.labels = sorted(glob.glob(os.path.join(root, "labels", "*")))
        self.exemplars_dict = _load_json(os.path.join(root, "exemplars.json"))

    def __len__(self):
        return len(self.labels)

    def _img_url(self, img_name):
        for ext in (".jpg", ".jpeg", ".png"):
            p = os.path.join(self.image_path, img_name + ext)
            if os.path.exists(p):
                return p
        return os.path.join(self.image_path, img_name)

    def __getitem__(self, idx):
        from PIL import Image

        label_file = self.labels[idx]
        img_name = os.path.basename(label_file).split(".")[0]
        img_url = self._img_url(img_name)
        image = Image.open(img_url).convert("RGB")

        rows = []
        with open(label_file) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 4:
                    rows.append([int(v) for v in parts])
        bboxes = np.array(rows, np.float32).reshape(-1, 4)
        ex = self.exemplars_dict[img_name][: self.max_exemplars]
        exemplars = np.array(ex, np.float32).reshape(-1, 4)
        return self._item(idx, img_name, img_url, image, bboxes, exemplars)


def build_dataset(cfg, split: str, eval_mode: Optional[bool] = None):
    """Dataset registry (reference datamodules/__init__.py:3-20 +
    datamodules.py dataset selection)."""
    eval_mode = cfg.eval if eval_mode is None else eval_mode
    kw = dict(
        image_size=cfg.image_size,
        max_exemplars=cfg.num_exemplars,
        eval_mode=eval_mode,
    )
    # accept the reference's spellings too (FSCD_LVIS_seen, datamodules
    # __init__.py:12-18) so its shell scripts port verbatim
    name = {"FSCD_LVIS_seen": "FSCD_LVIS_Seen",
            "FSCD_LVIS_unseen": "FSCD_LVIS_Unseen"}.get(
        cfg.dataset, cfg.dataset
    )
    if name == "FSCD147":
        return FSCD147Dataset(cfg.datapath, split=split, **kw)
    if name == "FSCD_LVIS_Seen":
        return FSCDLVISDataset(cfg.datapath, split=split, unseen=False, **kw)
    if name == "FSCD_LVIS_Unseen":
        return FSCDLVISDataset(cfg.datapath, split=split, unseen=True, **kw)
    if name == "RPINE":
        sub = "train" if split == "train" else "val"
        return RPINEDataset(
            os.path.join(cfg.datapath, sub),
            split="train" if split == "train" else "test",
            **kw,
        )
    raise KeyError(f"unknown dataset {name!r}")
