"""Datasets, preprocessing and host-side loading (reference datamodules/)."""

from tmr_tpu.data.coco_index import COCOIndex  # noqa: F401
from tmr_tpu.data.datasets import (  # noqa: F401
    FSCD147Dataset,
    FSCDLVISDataset,
    RPINEDataset,
    build_dataset,
)
from tmr_tpu.data.loader import DataLoader, collate  # noqa: F401
from tmr_tpu.data.transforms import normalize_image, resize_normalize  # noqa: F401
