"""Host-side data loading: seeded shuffling, thread prefetch, padded collate.

Replaces torch DataLoader + custom_collate (reference
datamodules/collate.py:3-21, abstract_datamodule.py:11-59). The reference
keeps boxes/exemplars as ragged python lists; jit wants fixed shapes, so the
collate pads GT boxes to ``max_gt`` with a validity mask and exemplars to
``max_exemplars``. Metadata stays a python list (host-only). Determinism
mirrors seed_everything + seeded workers: one np.random.Generator seeded
from (seed, epoch) drives the permutation.

Eval batches must be shape-uniform: items are grouped by their resolved
image size (1024 vs the 1536 escape hatch), which also preserves the
reference's val/test batch_size=1 behavior when batch_size=1.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np


def _gt_capacity(n: int, floor: int) -> int:
    """Smallest power-of-two bucket >= n (min ``floor``). GT boxes are NEVER
    truncated — dropping boxes would turn real objects into negative
    supervision in the target assignment (the reference keeps ragged lists
    of every box). Power-of-two growth bounds jit recompiles to a handful of
    bucket shapes even on FSC-147's few-thousand-object images."""
    cap = max(1, floor)
    while cap < n:
        cap *= 2
    return cap


def collate(items: list, max_gt: int, max_exemplars: int) -> dict:
    b = len(items)
    s = items[0]["image"].shape[0]
    image = np.stack([it["image"] for it in items])
    counts = [len(np.asarray(it["boxes"]).reshape(-1, 4)) for it in items]
    cap = _gt_capacity(max(counts, default=0), max_gt)
    gt_boxes = np.zeros((b, cap, 4), np.float32)
    gt_valid = np.zeros((b, cap), bool)
    exemplars = np.zeros((b, max_exemplars, 4), np.float32)
    for i, it in enumerate(items):
        boxes = np.asarray(it["boxes"], np.float32).reshape(-1, 4)
        gt_boxes[i, : len(boxes)] = boxes
        gt_valid[i, : len(boxes)] = True
        ex = np.asarray(it["exemplars"], np.float32).reshape(-1, 4)
        k = min(len(ex), max_exemplars)
        exemplars[i, :k] = ex[:k]
        if k == 0:
            raise ValueError(f"item {it['img_name']} has no exemplars")
        if k < max_exemplars:  # repeat last exemplar into padding slots
            exemplars[i, k:] = ex[k - 1]
    meta = [
        {k: it[k] for k in ("img_name", "img_url", "img_id", "img_size",
                            "orig_boxes", "orig_exemplars")}
        for it in items
    ]
    return {
        "image": image,
        "exemplars": exemplars,
        "gt_boxes": gt_boxes,
        "gt_valid": gt_valid,
        "meta": meta,
    }


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        seed: int = 42,
        max_gt: int = 800,
        max_exemplars: int = 1,
        num_workers: int = 4,
        drop_last: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.max_gt = max_gt
        self.max_exemplars = max_exemplars
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[dict]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = rng.permutation(n)

        window = self.num_workers * 2  # bounded submit-ahead: decoded images
        # are ~MBs each; scheduling the whole epoch up front would buffer
        # without limit when decoding outpaces the training step.
        from collections import deque

        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            queue: deque = deque()
            idx_iter = iter(order.tolist())
            for idx in idx_iter:
                queue.append(pool.submit(self.dataset.__getitem__, idx))
                if len(queue) >= window:
                    break
            pending: dict = {}
            while queue:
                it = queue.popleft().result()
                nxt = next(idx_iter, None)
                if nxt is not None:
                    queue.append(pool.submit(self.dataset.__getitem__, nxt))
                size = it["image"].shape[0]
                pending.setdefault(size, []).append(it)
                if len(pending[size]) == self.batch_size:
                    yield collate(pending.pop(size), self.max_gt,
                                  self.max_exemplars)
            if not self.drop_last:
                for group in pending.values():
                    if group:
                        yield collate(group, self.max_gt, self.max_exemplars)
