"""ctypes bindings for the native IO runtime (native/tmr_io.cc).

A C++ thread pool streams tar shards (the reference's `hadoop fs -get` +
tarfile layer, mapper.py:71-75) with inline ustar parsing and a bounded
prefetch queue, so storage IO and tar decoding overlap device compute
outside the GIL. The Python side receives (shard_index, member_name, bytes)
and keeps image decoding in PIL (decode is a small fraction of the byte
shuffling; the payload copy out of C is one memcpy).

The library is built lazily with the in-image g++ (``ensure_built``); when
no compiler or prebuilt .so is available every consumer falls back to the
pure-Python tarfile path, so the framework never hard-depends on the native
layer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Optional, Sequence, Tuple

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libtmr_io.so")
_lib = None


class _Item(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("size", ctypes.c_int64),
        ("shard", ctypes.c_int32),
    ]


def ensure_built(quiet: bool = True) -> Optional[str]:
    """Build libtmr_io.so if missing; returns its path or None (no g++)."""
    if os.path.exists(_SO_PATH):
        return _SO_PATH
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=quiet,
        )
    except (OSError, subprocess.CalledProcessError):
        return None
    return _SO_PATH if os.path.exists(_SO_PATH) else None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built()
    if path is None:
        raise OSError("native IO library unavailable (no g++/make)")
    lib = ctypes.CDLL(path)
    lib.tmr_io_open.restype = ctypes.c_void_p
    lib.tmr_io_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.tmr_io_next.restype = ctypes.c_int
    lib.tmr_io_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(_Item)]
    lib.tmr_io_free_item.argtypes = [ctypes.POINTER(_Item)]
    lib.tmr_io_error.restype = ctypes.c_int
    lib.tmr_io_error.argtypes = [ctypes.c_void_p]
    lib.tmr_io_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


class NativeTarStream:
    """Iterate (shard_index, member_name, payload bytes) over tar shards,
    decoded and prefetched by the C++ thread pool.

    Unreadable shards are skipped and counted (``errors``) — the same
    skip-and-log tolerance as the Python path (mapper.py:79-81).
    """

    def __init__(self, paths: Sequence[str], threads: int = 4,
                 queue_cap: int = 64):
        lib = _load()
        self._lib = lib
        self._errors_at_close = 0
        self._handle = None
        self._paths = [os.fsencode(p) for p in paths]
        arr = (ctypes.c_char_p * len(self._paths))(*self._paths)
        self._handle = lib.tmr_io_open(arr, len(self._paths), threads,
                                       queue_cap)
        if not self._handle:
            raise OSError("tmr_io_open failed")

    def __iter__(self) -> Iterator[Tuple[int, str, bytes]]:
        item = _Item()
        while True:
            rc = self._lib.tmr_io_next(self._handle, ctypes.byref(item))
            if rc == 0:
                return
            try:
                name = item.name.decode("utf-8", "replace")
                data = ctypes.string_at(item.data, item.size)
            finally:
                self._lib.tmr_io_free_item(ctypes.byref(item))
            yield int(item.shard), name, data

    @property
    def errors(self) -> int:
        if self._handle is None:
            return self._errors_at_close
        return int(self._lib.tmr_io_error(self._handle))

    def close(self) -> None:
        if self._handle:
            self._errors_at_close = int(
                self._lib.tmr_io_error(self._handle)
            )
            self._lib.tmr_io_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
