"""Durable per-shard journal for the map phase — the crash-resume ledger.

Hadoop got task re-execution for free from the JobTracker; our streaming
replacement gets it from a directory of tiny JSON done-markers, one per
shard, written atomically (tmp + ``os.replace``) AFTER the shard's last
feature ``.npy`` has landed. A marker records everything the reducer needs
from that shard — the float64 category stat sums, the image count, the
skipped/non-finite tallies — plus a digest over those payload fields, so
``map --resume`` can fold journaled shards straight into the accumulator
without re-encoding and still produce a byte-identical stats table
(float64 values survive the JSON round-trip exactly; a truncated or
hand-edited marker fails the digest check and the shard simply re-runs).

Layout: ``<features_out>/_journal/<shard-stem>.json`` by default
(``--journal_dir`` overrides). Write ordering is the correctness
contract: features first, marker last — a crash between the two re-does
the shard, which is safe because feature writes are atomic + idempotent.

Elastic (multi-worker) extension: a marker can carry WHO committed it —
optional ``worker``/``epoch`` fields (parallel/elastic.py lease epochs).
Both are outside the digest's field set, so old markers (no fields)
still validate and ``--resume`` folds them unchanged. ``record`` also
takes a ``fence`` callable, invoked right before the marker touches
disk: a fence that raises (``StaleLeaseError`` — the worker's lease was
revoked and the shard reassigned under a higher epoch) aborts the
commit with NO marker written, which is what keeps a paused-then-resumed
writer from vouching for a shard it no longer owns.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Dict, Optional

from tmr_tpu.utils import faults
from tmr_tpu.utils.atomicio import atomic_write

#: schema tag stamped on every done-marker — bump on incompatible change
MAP_JOURNAL_SCHEMA = "map_journal/v1"


class StaleLeaseError(RuntimeError):
    """A journal commit was fenced: the committing worker's lease epoch
    is no longer current (revoked after a stale heartbeat / worker exit,
    or the shard was already committed by a straggler duplicate). The
    attempt must NOT retry — the shard belongs to someone else now —
    so the map executor treats this as non-retryable."""

#: payload fields covered by the digest (order matters — it is the
#: canonical serialization the digest is computed over)
_DIGEST_FIELDS = (
    "shard", "category", "images", "skipped_images", "skipped_members",
    "nonfinite_images", "sums",
)


def _digest(entry: dict) -> str:
    blob = json.dumps(
        [entry.get(k) for k in _DIGEST_FIELDS], sort_keys=True
    ).encode()
    return hashlib.sha256(blob).hexdigest()


def shard_stem(shard_name: str) -> str:
    """Marker filename stem for a shard (path separators flattened so a
    nested shard name cannot escape the journal directory)."""
    base = os.path.basename(shard_name)
    if base.endswith(".tar"):
        base = base[: -len(".tar")]
    return base.replace(os.sep, "_").replace("/", "_") or "_unnamed"


class ShardJournal:
    """Read/write the per-shard done-markers under one directory."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, shard_name: str) -> str:
        return os.path.join(self.directory, shard_stem(shard_name) + ".json")

    def record(
        self,
        shard_name: str,
        category: int,
        sums,
        images: int,
        skipped_images: int = 0,
        skipped_members: int = 0,
        nonfinite_images: int = 0,
        attempts: int = 1,
        wall_s: float = 0.0,
        worker: Optional[str] = None,
        epoch: Optional[int] = None,
        fence: Optional[Callable[[], None]] = None,
    ) -> dict:
        """Atomically commit the done-marker for one shard. The ``journal``
        fault point fires before anything touches disk, so an injected
        journal failure leaves no marker at all (the shard re-runs).
        ``fence`` (when given) runs after the fault point and before the
        write: raising (StaleLeaseError) aborts the commit marker-less —
        the stale-epoch rejection the elastic coordinator counts."""
        faults.fire("journal")
        if fence is not None:
            fence()
        entry = {
            "schema": MAP_JOURNAL_SCHEMA,
            "shard": shard_name,
            "category": int(category),
            "images": int(images),
            "skipped_images": int(skipped_images),
            "skipped_members": int(skipped_members),
            "nonfinite_images": int(nonfinite_images),
            "sums": [float(v) for v in sums],
            "attempts": int(attempts),
            "wall_s": float(wall_s),
        }
        if worker is not None:
            entry["worker"] = str(worker)
        if epoch is not None:
            entry["epoch"] = int(epoch)
        entry["digest"] = _digest(entry)
        atomic_write(self._path(shard_name), lambda f: json.dump(entry, f))
        return entry

    def invalidate(self, shard_name: str) -> None:
        """Remove a shard's done-marker (if any) — called when the shard
        is quarantined so a marker from an EARLIER successful run cannot
        vouch for features a later run just cleaned up."""
        try:
            os.unlink(self._path(shard_name))
        except FileNotFoundError:
            pass

    def done(self, shard_name: str) -> Optional[dict]:
        """The validated done-marker for a shard, or None when missing,
        unparseable, schema-mismatched, or digest-corrupt — all of which
        mean 'not done, run it again'."""
        path = self._path(shard_name)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(entry, dict):
            return None
        if entry.get("schema") != MAP_JOURNAL_SCHEMA:
            return None
        if entry.get("digest") != _digest(entry):
            return None
        return entry

    def load_all(self) -> Dict[str, dict]:
        """Every valid marker in the directory, keyed by recorded shard
        name (diagnostics/debug — resume uses per-shard ``done``)."""
        out: Dict[str, dict] = {}
        for fn in sorted(os.listdir(self.directory)):
            if not fn.endswith(".json"):
                continue
            stem = fn[: -len(".json")]
            entry = self.done(stem + ".tar")
            if entry is not None:
                out[entry["shard"]] = entry
        return out
