"""Sharded streaming feature extraction — the Hadoop MapReduce replacement.

Reference pipeline (mapper.py + reducer.py under Hadoop Streaming):
  shard list on stdin -> mapper per tar: HDFS get, untar, per image
  ONNX ViT-B encode (batch 1) -> 4 stats (mean/std/max/sparsity,
  mapper.py:103-114) summed per category + .npy feature dumps ->
  "category\\tsum_mean,sum_std,sum_max,sum_spar,count" (:138) ->
  Hadoop sort/shuffle -> reducer group-by-category averages table
  (reducer.py:25-27).

TPU-native redesign:
- the per-image ONNX session becomes the jitted Flax encoder, batched;
- a shard is a work item on a host feeder thread (tarfile + PIL);
- the sort/shuffle collapses into a 3x5 per-category stat matrix summed on
  device — when running over a mesh, each device accumulates partials for
  its shard subset and one ``jax.lax.psum`` over the 'data' axis replaces
  the entire Hadoop shuffle;
- the reducer is a pure formatting function over the final (3, 5) matrix,
  emitting the identical table.
"""

from __future__ import annotations

import io
import os
import tarfile
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

CATEGORIES = ("Easy", "Normal", "Hard", "Unknown")  # mapper.py:15-20
STAT_NAMES = ("sum_mean", "sum_std", "sum_max", "sum_spar", "count")


def category_of(shard_name: str) -> int:
    folder = os.path.basename(shard_name).replace(".tar", "")
    for i, c in enumerate(CATEGORIES[:3]):
        if folder.startswith(c + "_"):
            return i
    return 3


def preprocess_image(data: bytes, size: int = 1024) -> Optional[np.ndarray]:
    """PIL decode -> resize -> /255 (mapper.py:22-30), NHWC float32."""
    from PIL import Image

    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((size, size))
        return np.asarray(img, np.float32) / 255.0
    except Exception:
        return None  # bad image -> skip, like mapper.py:31-32


def iter_tar_images(
    path: str, size: int = 1024
) -> Iterator[tuple[str, np.ndarray]]:
    """Stream (name, image) from a tar shard; corrupt members skipped."""
    with tarfile.open(path, "r") as tar:
        for member in tar:
            if not member.isfile():
                continue
            if not member.name.lower().endswith((".png", ".jpg", ".jpeg")):
                continue
            data = tar.extractfile(member)
            if data is None:
                continue
            img = preprocess_image(data.read(), size)
            if img is not None:
                yield member.name, img


def feature_stats(features: jnp.ndarray) -> jnp.ndarray:
    """(B, ...) -> (B, 4) [mean, std, max, sparsity] per image.

    Sparsity = fraction of elements <= 0 (mapper.py:107); std is the
    population std like np.std.
    """
    b = features.shape[0]
    flat = features.reshape(b, -1).astype(jnp.float32)
    mean = flat.mean(axis=1)
    std = jnp.sqrt(((flat - mean[:, None]) ** 2).mean(axis=1))
    mx = flat.max(axis=1)
    spar = (flat <= 0).mean(axis=1)
    return jnp.stack([mean, std, mx, spar], axis=1)


def make_encode_stats_fn(encoder, params) -> Callable:
    """Jitted (images (B,S,S,3)) -> ((B,...) features, (B,4) stats)."""

    @jax.jit
    def run(images):
        feats = encoder.apply({"params": params}, images)
        return feats, feature_stats(feats)

    return run


def make_encode_stats_fn_from_artifact(path: str) -> Callable:
    """Worker-side encode fn from a serialized artifact (export_encoder.py) —
    the onnxruntime-session equivalent of mapper.py:40-45: no model code or
    weights needed on the worker, just the artifact file."""
    from tmr_tpu.utils.export import load_exported

    encoder = load_exported(path)

    @jax.jit
    def run(images):
        feats = encoder(images)
        return feats, feature_stats(feats)

    return run


class StatAccumulator:
    """Per-category running sums — the mapper emit + reducer aggregation
    state, as a dense (4 categories x 5 values) matrix."""

    def __init__(self):
        self.table = np.zeros((len(CATEGORIES), len(STAT_NAMES)), np.float64)

    def add(self, category: int, stats: np.ndarray) -> None:
        """stats: (B, 4) per-image values for one shard batch."""
        self.table[category, :4] += stats.sum(axis=0)
        self.table[category, 4] += len(stats)

    def merge(self, other: "StatAccumulator") -> None:
        self.table += other.table

    def emit_lines(self) -> list[str]:
        """The mapper's shuffle records (mapper.py:138), for parity/debug."""
        lines = []
        for i, cat in enumerate(CATEGORIES):
            m, s, x, sp, n = self.table[i]
            if n > 0:
                lines.append(f"{cat}\t{m},{s},{x},{sp},{int(n)}")
        return lines


def format_stats_table(sums_by_key: dict) -> str:
    """Averages table over {key: (5,) sums} exactly like reducer.py:25-27."""
    out = [
        f"{'CATEGORY':<12} | {'IMAGES':>6} | "
        f"{'AVG_MEAN':>8} | {'AVG_STD':>8} | "
        f"{'AVG_MAX':>8} | {'SPARSITY':>9}",
        "-" * 70,
    ]
    for cat, sums in sums_by_key.items():
        n = sums[4]
        if n <= 0:
            continue
        avg = np.asarray(sums[:4]) / n
        out.append(
            f"{cat:<12} | {int(n):>6} | "
            f"{avg[0]:>8.4f} | {avg[1]:>8.4f} | "
            f"{avg[2]:>8.4f} | {avg[3]:>7.2%}"
        )
    return "\n".join(out)


def reducer_table(table: np.ndarray) -> str:
    """Format a StatAccumulator matrix (reducer.py:25-27,39-42)."""
    return format_stats_table(
        {cat: table[i] for i, cat in enumerate(CATEGORIES)}
    )


def reduce_lines(lines: Iterable[str]) -> dict:
    """The reducer's group-by-key aggregation (reducer.py:47-92) over
    ``category\\tsum_mean,sum_std,sum_max,sum_spar,count`` records.

    Unlike Hadoop's sorted-stream protocol, input need not be sorted (we
    aggregate in a dict — the 'shuffle' is free on one host). Malformed
    lines are logged and skipped (reducer.py:53-76)."""
    from tmr_tpu.utils.profiling import log_warning

    sums: dict = {}
    for raw in lines:
        line = raw.strip()
        if not line:
            continue
        try:
            key, payload = line.split("\t")
            vals = [float(v) for v in payload.split(",")]
            if len(vals) != 5:
                raise ValueError(f"expected 5 values, got {len(vals)}")
        except Exception as e:
            log_warning(f"skipping malformed line {line!r}: {e}")
            continue
        acc = sums.setdefault(key, np.zeros(5, np.float64))
        acc += np.asarray(vals, np.float64)
    return sums


def run_stream(
    shard_paths: Sequence[str],
    encode_stats_fn: Callable,
    batch_size: int = 8,
    image_size: int = 1024,
    save_features: Optional[Callable[[str, str, np.ndarray], None]] = None,
    feeder_threads: int = 4,
) -> StatAccumulator:
    """Single-host streaming map phase over tar shards.

    Host feeder threads decode shards ahead of the device; the device runs
    the jitted encoder on fixed-size batches (short tails padded and
    masked out of the stats). ``save_features(shard, image_name, features)``
    is the .npy side-effect hook (mapper.py:117-118).
    """
    from tmr_tpu.utils.profiling import log_progress, log_warning

    acc = StatAccumulator()

    def load_shard(path):
        # bad/missing tar -> log + skip the whole shard (mapper.py:79-81)
        try:
            return list(iter_tar_images(path, image_size))
        except Exception as e:
            log_warning(f"skipping shard {path}: {e}")
            return []

    from collections import deque

    with ThreadPoolExecutor(max_workers=feeder_threads) as pool:
        # bounded shard prefetch — whole decoded shards are large
        queue: deque = deque()
        path_iter = iter(shard_paths)
        for path in path_iter:
            queue.append((path, pool.submit(load_shard, path)))
            if len(queue) >= feeder_threads + 1:
                break
        while queue:
            path, fut = queue.popleft()
            images = fut.result()
            nxt = next(path_iter, None)
            if nxt is not None:
                queue.append((nxt, pool.submit(load_shard, nxt)))
            cat = category_of(path)
            log_progress(
                f"shard {os.path.basename(path)}: {len(images)} images "
                f"({CATEGORIES[cat]})"
            )
            for i in range(0, len(images), batch_size):
                chunk = images[i : i + batch_size]
                names = [n for n, _ in chunk]
                arr = np.stack([im for _, im in chunk])
                real = len(arr)
                if real < batch_size:  # pad to the jitted batch shape
                    pad = np.zeros(
                        (batch_size - real,) + arr.shape[1:], arr.dtype
                    )
                    arr = np.concatenate([arr, pad])
                feats, stats = encode_stats_fn(jnp.asarray(arr))
                stats = np.asarray(stats)[:real]
                acc.add(cat, stats)
                if save_features is not None:
                    f_np = np.asarray(feats)[:real]
                    for name, feat in zip(names, f_np):
                        save_features(os.path.basename(path), name, feat)
    return acc


def run_stream_native(
    shard_paths: Sequence[str],
    encode_stats_fn: Callable,
    batch_size: int = 8,
    image_size: int = 1024,
    save_features: Optional[Callable[[str, str, np.ndarray], None]] = None,
    feeder_threads: int = 4,
) -> StatAccumulator:
    """run_stream on the native C++ IO runtime (native/tmr_io.cc): tar
    parsing + prefetch happen in a C++ thread pool outside the GIL; Python
    only decodes images and feeds the device. Members from different shards
    interleave (workers stream shards concurrently) — per-item category
    tracking keeps the stats identical to the sequential path."""
    from tmr_tpu.data.native_io import NativeTarStream
    from tmr_tpu.utils.profiling import log_warning

    acc = StatAccumulator()
    cats = [category_of(p) for p in shard_paths]
    shard_names = [os.path.basename(p) for p in shard_paths]
    buf_imgs: list = []
    buf_meta: list = []

    def flush():
        if not buf_imgs:
            return
        real = len(buf_imgs)
        arr = np.stack(buf_imgs)
        if real < batch_size:
            pad = np.zeros((batch_size - real,) + arr.shape[1:], arr.dtype)
            arr = np.concatenate([arr, pad])
        feats, stats = encode_stats_fn(jnp.asarray(arr))
        stats = np.asarray(stats)[:real]
        for (cat, _, _), row in zip(buf_meta, stats):
            acc.add(cat, row[None])
        if save_features is not None:
            f_np = np.asarray(feats)[:real]
            for (_, shard, name), feat in zip(buf_meta, f_np):
                save_features(shard, name, feat)
        buf_imgs.clear()
        buf_meta.clear()

    with NativeTarStream(shard_paths, threads=feeder_threads) as stream:
        for shard_idx, member, data in stream:
            if not member.lower().endswith((".png", ".jpg", ".jpeg")):
                continue
            img = preprocess_image(data, image_size)
            if img is None:
                continue
            buf_imgs.append(img)
            buf_meta.append((cats[shard_idx], shard_names[shard_idx], member))
            if len(buf_imgs) == batch_size:
                flush()
        flush()
        if stream.errors:
            log_warning(f"{stream.errors} unreadable shards skipped")
    return acc


def allreduce_stats(table: jnp.ndarray, axis_name: str = "data") -> jnp.ndarray:
    """The shuffle replacement: psum per-device (4, 5) partials over the
    mesh axis. Use inside shard_map/pmap; see tests/test_parallel.py."""
    return jax.lax.psum(table, axis_name)


# --------------------------------------------------------------------- CLI
# Hadoop-Streaming-compatible entry points:
#   cat list_tars.txt | python -m tmr_tpu.parallel.mapreduce map \
#       --data_dir /data/tars --artifact exported/encoder.stablehlo \
#       --features_out features_output \
#   | sort | python -m tmr_tpu.parallel.mapreduce reduce
# The map phase reads tar names from stdin (mapper.py:51), prefixes
# --data_dir (the `hadoop fs -get` replacement: a posix/NFS/FUSE path),
# streams every shard through the jitted encoder, writes per-image feature
# .npy files under features_out/<category>/ (mapper.py:126-130), and emits
# aggregated `category\tsums,count` records (mapper.py:138; aggregated
# per-run rather than per-tar — reduce semantics are identical since the
# reducer sums). The reduce phase needs no sort (dict aggregation) but
# tolerates sorted Hadoop-style streams identically.


def _cli_map(args) -> int:
    import sys

    from tmr_tpu.utils.profiling import log_info, log_warning

    names = [ln.strip() for ln in sys.stdin if ln.strip()]
    paths = [
        n if os.path.isabs(n) else os.path.join(args.data_dir, n)
        for n in names
    ]
    log_info(f"map: {len(paths)} shards from stdin")

    if args.artifact:
        fn = make_encode_stats_fn_from_artifact(args.artifact)
    else:
        from tmr_tpu.models import build_sam_encoder

        if not args.checkpoint:
            log_warning("map: no --artifact/--checkpoint, random weights")
        model, params = build_sam_encoder(
            args.model_type, args.checkpoint, args.image_size
        )
        fn = make_encode_stats_fn(model, params)

    save = None
    if args.features_out:

        def save(shard: str, name: str, feat: np.ndarray) -> None:
            cat = CATEGORIES[category_of(shard)]
            d = os.path.join(args.features_out, cat,
                             shard.replace(".tar", ""))
            os.makedirs(d, exist_ok=True)
            base = os.path.splitext(os.path.basename(name))[0]
            np.save(os.path.join(d, base + ".npy"), feat)

    use_native = not args.no_native
    if use_native:
        from tmr_tpu.data import native_io

        use_native = native_io.available()
        if not use_native:
            log_info("native IO unavailable; using the Python tarfile path")
    runner = run_stream_native if use_native else run_stream
    acc = runner(
        paths, fn, batch_size=args.batch_size, image_size=args.image_size,
        save_features=save, feeder_threads=args.feeder_threads,
    )
    for line in acc.emit_lines():
        print(line)
    return 0


def _cli_reduce(_args) -> int:
    import sys

    sums = reduce_lines(sys.stdin)
    print(format_stats_table(sums))
    return 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tmr_tpu.parallel.mapreduce",
        description="Streaming feature extraction (Hadoop mapper/reducer "
                    "replacement)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("map", help="tar names on stdin -> stat records")
    m.add_argument("--data_dir", default=".",
                   help="prefix for shard names (the HDFS tar directory)")
    m.add_argument("--artifact", default=None,
                   help="serialized encoder from export_encoder.py")
    m.add_argument("--checkpoint", default=None)
    m.add_argument("--model_type", default="vit_b")
    m.add_argument("--features_out", default=None,
                   help="write per-image feature .npy under "
                        "<dir>/<category>/<shard>/ (mapper.py:126-130)")
    m.add_argument("--batch_size", default=8, type=int)
    m.add_argument("--image_size", default=1024, type=int)
    m.add_argument("--feeder_threads", default=4, type=int)
    m.add_argument("--no_native", action="store_true",
                   help="force the Python tarfile path instead of the C++ "
                        "IO runtime (native/tmr_io.cc)")
    sub.add_parser("reduce", help="stat records on stdin -> averages table")
    args = p.parse_args(argv)
    return _cli_map(args) if args.cmd == "map" else _cli_reduce(args)


if __name__ == "__main__":
    raise SystemExit(main())
