"""Sharded streaming feature extraction — the Hadoop MapReduce replacement.

Reference pipeline (mapper.py + reducer.py under Hadoop Streaming):
  shard list on stdin -> mapper per tar: HDFS get, untar, per image
  ONNX ViT-B encode (batch 1) -> 4 stats (mean/std/max/sparsity,
  mapper.py:103-114) summed per category + .npy feature dumps ->
  "category\\tsum_mean,sum_std,sum_max,sum_spar,count" (:138) ->
  Hadoop sort/shuffle -> reducer group-by-category averages table
  (reducer.py:25-27).

TPU-native redesign:
- the per-image ONNX session becomes the jitted Flax encoder, batched;
- a shard is a work item on a host feeder thread (tarfile + PIL);
- the sort/shuffle collapses into a 3x5 per-category stat matrix summed on
  device — when running over a mesh, each device accumulates partials for
  its shard subset and one ``jax.lax.psum`` over the 'data' axis replaces
  the entire Hadoop shuffle;
- the reducer is a pure formatting function over the final (3, 5) matrix,
  emitting the identical table.

Fault tolerance (the JobTracker replacement): every shard runs through a
retrying executor — per-shard attempt loop with exponential backoff +
deterministic jitter, a per-shard STALL timeout on the load half (a hung
NFS/FUSE read parks a daemon thread instead of wedging the run, while a
merely-slow shard keeps its heartbeat and never times out), bounded
retries, then quarantine with a recorded cause instead of aborting —
partial feature files of a quarantined shard are cleaned up so disk
reconciles with the table. Feature ``.npy`` writes are atomic (tmp + ``os.replace``) and
idempotent; a durable journal (parallel/journal.py) commits a per-shard
done-marker after the shard's last feature lands, and ``resume=True``
folds journaled shards into the accumulator without re-encoding —
byte-identically, because shards accumulate into the table as one float64
vector each. Non-finite encoder outputs (the skip-nonfinite containment
from train/state.py, applied to inference) are excluded per image from
the category sums and counted. Everything is observable through a
``map_report/v1`` document (diagnostics.MAP_REPORT_SCHEMA) and provable
with the deterministic fault-injection points threaded through this file
(utils/faults.py; exercised by scripts/chaos_probe.py).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import tarfile
import threading
import time
from collections import deque
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu import obs
from tmr_tpu.diagnostics import MAP_REPORT_SCHEMA
from tmr_tpu.parallel.journal import StaleLeaseError
from tmr_tpu.utils import faults
from tmr_tpu.utils.atomicio import atomic_write

CATEGORIES = ("Easy", "Normal", "Hard", "Unknown")  # mapper.py:15-20
STAT_NAMES = ("sum_mean", "sum_std", "sum_max", "sum_spar", "count")

#: deterministic failures retrying cannot heal (a structurally corrupt
#: tar, a shard path that does not exist, a journal commit fenced off by
#: a revoked lease epoch — the shard belongs to another worker now) —
#: quarantine on first sight instead of burning the whole backoff budget
_NON_RETRYABLE = (
    tarfile.ReadError,
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    StaleLeaseError,
)


def category_of(shard_name: str) -> int:
    folder = os.path.basename(shard_name).replace(".tar", "")
    for i, c in enumerate(CATEGORIES[:3]):
        if folder.startswith(c + "_"):
            return i
    return 3


def preprocess_image(data: bytes, size: int = 1024) -> Optional[np.ndarray]:
    """PIL decode -> resize -> /255 (mapper.py:22-30), NHWC float32."""
    from PIL import Image

    faults.fire("decode")
    data = faults.corrupt_bytes("decode", data)
    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((size, size))
        return np.asarray(img, np.float32) / 255.0
    except Exception:
        return None  # bad image -> skip, like mapper.py:31-32


def _bump(counts: Optional[dict], key: str) -> None:
    if counts is not None:
        counts[key] = counts.get(key, 0) + 1


def iter_tar_images(
    path: str, size: int = 1024, counts: Optional[dict] = None,
    heartbeat: Optional[Callable[[], None]] = None,
) -> Iterator[tuple[str, np.ndarray]]:
    """Stream (name, image) from a tar shard; corrupt members skipped.

    ``counts`` (when given) tallies what was dropped — the reference
    pipeline silently ate corrupt images, so a half-corrupt dataset looked
    identical to a clean one: ``skipped_members`` (image-named members
    whose payload could not be read out of the tar) and
    ``skipped_images`` (payloads PIL could not decode).

    ``heartbeat`` (when given) is called once per member SCANNED —
    including skipped/non-image/undecodable ones — so the executor's
    stall detector sees progress whenever the tar read advances, not
    only when an image survives decode.
    """
    with tarfile.open(path, "r") as tar:
        for member in tar:
            if heartbeat is not None:
                heartbeat()
            if not member.isfile():
                continue
            if not member.name.lower().endswith((".png", ".jpg", ".jpeg")):
                continue
            data = tar.extractfile(member)
            if data is None:
                _bump(counts, "skipped_members")
                continue
            raw = data.read()
            faults.fire("tar.member")
            raw = faults.corrupt_bytes("tar.member", raw)
            img = preprocess_image(raw, size)
            if img is None:
                _bump(counts, "skipped_images")
                continue
            yield member.name, img


def feature_stats(features: jnp.ndarray) -> jnp.ndarray:
    """(B, ...) -> (B, 4) [mean, std, max, sparsity] per image.

    Sparsity = fraction of elements <= 0 (mapper.py:107); std is the
    population std like np.std.
    """
    b = features.shape[0]
    flat = features.reshape(b, -1).astype(jnp.float32)
    mean = flat.mean(axis=1)
    std = jnp.sqrt(((flat - mean[:, None]) ** 2).mean(axis=1))
    mx = flat.max(axis=1)
    spar = (flat <= 0).mean(axis=1)
    return jnp.stack([mean, std, mx, spar], axis=1)


def make_encode_stats_fn(encoder, params) -> Callable:
    """Jitted (images (B,S,S,3)) -> ((B,...) features, (B,4) stats)."""

    @jax.jit
    def run(images):
        feats = encoder.apply({"params": params}, images)
        return feats, feature_stats(feats)

    return run


def make_encode_stats_fn_from_artifact(path: str) -> Callable:
    """Worker-side encode fn from a serialized artifact (export_encoder.py) —
    the onnxruntime-session equivalent of mapper.py:40-45: no model code or
    weights needed on the worker, just the artifact file."""
    from tmr_tpu.utils.export import load_exported

    encoder = load_exported(path)

    @jax.jit
    def run(images):
        feats = encoder(images)
        return feats, feature_stats(feats)

    return run


class StatAccumulator:
    """Per-category running sums — the mapper emit + reducer aggregation
    state, as a dense (4 categories x 5 values) matrix."""

    def __init__(self):
        self.table = np.zeros((len(CATEGORIES), len(STAT_NAMES)), np.float64)

    def add(self, category: int, stats: np.ndarray) -> None:
        """stats: (B, 4) per-image values for one shard batch."""
        self.table[category, :4] += stats.sum(axis=0)
        self.table[category, 4] += len(stats)

    def add_totals(self, category: int, sums) -> None:
        """Fold one shard's finished (5,) float64 sums in as a single
        addition — the resume-equivalence unit: a journaled shard replays
        into the table with exactly the float64 addition its live run
        performed, so resumed tables come out byte-identical."""
        self.table[category] += np.asarray(sums, np.float64)

    def merge(self, other: "StatAccumulator") -> None:
        self.table += other.table

    def emit_lines(self) -> list[str]:
        """The mapper's shuffle records (mapper.py:138), for parity/debug."""
        lines = []
        for i, cat in enumerate(CATEGORIES):
            m, s, x, sp, n = self.table[i]
            if n > 0:
                lines.append(f"{cat}\t{m},{s},{x},{sp},{int(n)}")
        return lines


def format_stats_table(sums_by_key: dict) -> str:
    """Averages table over {key: (5,) sums} exactly like reducer.py:25-27."""
    out = [
        f"{'CATEGORY':<12} | {'IMAGES':>6} | "
        f"{'AVG_MEAN':>8} | {'AVG_STD':>8} | "
        f"{'AVG_MAX':>8} | {'SPARSITY':>9}",
        "-" * 70,
    ]
    for cat, sums in sums_by_key.items():
        n = sums[4]
        if n <= 0:
            continue
        avg = np.asarray(sums[:4]) / n
        out.append(
            f"{cat:<12} | {int(n):>6} | "
            f"{avg[0]:>8.4f} | {avg[1]:>8.4f} | "
            f"{avg[2]:>8.4f} | {avg[3]:>7.2%}"
        )
    return "\n".join(out)


def reducer_table(table: np.ndarray) -> str:
    """Format a StatAccumulator matrix (reducer.py:25-27,39-42)."""
    return format_stats_table(
        {cat: table[i] for i, cat in enumerate(CATEGORIES)}
    )


def reduce_lines(lines: Iterable[str]) -> dict:
    """The reducer's group-by-key aggregation (reducer.py:47-92) over
    ``category\\tsum_mean,sum_std,sum_max,sum_spar,count`` records.

    Unlike Hadoop's sorted-stream protocol, input need not be sorted (we
    aggregate in a dict — the 'shuffle' is free on one host). Malformed
    lines are logged and skipped (reducer.py:53-76)."""
    from tmr_tpu.utils.profiling import log_warning

    sums: dict = {}
    for raw in lines:
        line = raw.strip()
        if not line:
            continue
        try:
            key, payload = line.split("\t")
            vals = [float(v) for v in payload.split(",")]
            if len(vals) != 5:
                raise ValueError(f"expected 5 values, got {len(vals)}")
        except Exception as e:
            log_warning(f"skipping malformed line {line!r}: {e}")
            continue
        acc = sums.setdefault(key, np.zeros(5, np.float64))
        acc += np.asarray(vals, np.float64)
    return sums


# ------------------------------------------------------------ retry policy
def backoff_delay(
    attempt: int,
    base: float = 0.5,
    cap: float = 30.0,
    jitter: float = 0.5,
    key: int = 0,
) -> float:
    """Delay before retry number ``attempt`` (1 = first retry): capped
    exponential ``min(cap, base * 2**(attempt-1))`` plus a deterministic
    jitter fraction in [0, jitter] of the capped delay, keyed on
    (key, attempt) so replays sleep identically and concurrent runs
    decorrelate by key."""
    import random

    d = min(cap, base * (2.0 ** max(attempt - 1, 0)))
    if jitter > 0.0:
        d *= 1.0 + jitter * random.Random(int(key) * 1000003 + attempt).random()
    return d


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Shard-level retry/backoff/timeout knobs for the map executor.

    ``shard_timeout`` is a STALL budget on each attempt's load half
    (tar open/read/decode — the hang-prone IO): the attempt fails when
    the loader makes no member progress for that many seconds, so a
    big-but-healthy shard that simply takes long never times out, while
    a hung NFS/FUSE read does. None disables. ``max_attempts`` bounds
    tries before quarantine."""

    max_attempts: int = 3
    shard_timeout: Optional[float] = None
    backoff_base: float = 0.5
    backoff_max: float = 30.0
    backoff_jitter: float = 0.5
    seed: int = 0

    def delay(self, shard_index: int, attempt: int) -> float:
        return backoff_delay(
            attempt,
            base=self.backoff_base,
            cap=self.backoff_max,
            jitter=self.backoff_jitter,
            key=(self.seed << 20) ^ shard_index,
        )


class MapReport:
    """Builder for the ``map_report/v1`` document — per-shard records in
    shard-list order plus aggregate totals (diagnostics.MAP_REPORT_SCHEMA
    documents the schema; diagnostics.validate_map_report checks it)."""

    def __init__(self):
        self.shards: List[dict] = []

    def add(self, record: dict) -> None:
        self.shards.append(record)

    def document(self) -> dict:
        """The map_report/v1 document. Carries a ``metrics`` key — the
        process-wide registry snapshot (metrics_report/v1) at document
        time — so one report line holds shard accounting AND counter
        state (validated together by ``validate_map_report``)."""
        shards = sorted(self.shards, key=lambda r: r.get("index", 0))
        totals = {
            "shards": len(shards),
            "ok": sum(1 for r in shards if r["status"] == "ok"),
            "quarantined": sum(
                1 for r in shards if r["status"] == "quarantined"
            ),
            "resumed": sum(1 for r in shards if r["status"] == "resumed"),
            "images": sum(r["images"] for r in shards),
            "skipped_images": sum(r["skipped_images"] for r in shards),
            "skipped_members": sum(
                r.get("skipped_members", 0) for r in shards
            ),
            "nonfinite_images": sum(r["nonfinite_images"] for r in shards),
            "retries": sum(max(r["attempts"] - 1, 0) for r in shards),
            "wall_s": sum(r["wall_s"] for r in shards),
        }
        doc = {
            "schema": MAP_REPORT_SCHEMA,
            "shards": shards,
            "quarantined": [
                r["shard"] for r in shards if r["status"] == "quarantined"
            ],
            "resumed": [
                r["shard"] for r in shards if r["status"] == "resumed"
            ],
            "totals": totals,
            "metrics": obs.get_registry().snapshot(),
        }
        if obs.flight_enabled():
            # the flight recorder's device-time attribution for every
            # program this run executed, as one mfu_report/v1 — the map
            # phase's achieved-FLOP/s accounting rides its own report
            # (validate_map_report validates the attachment)
            doc["mfu"] = obs.mfu_report()
        return doc

    def write(self, path: str) -> None:
        doc = self.document()

        def dump(f):
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

        atomic_write(path, dump)

    def summary_line(self) -> str:
        t = self.document()["totals"]
        return (
            f"map: {t['ok']} ok / {t['resumed']} resumed / "
            f"{t['quarantined']} quarantined of {t['shards']} shards; "
            f"{t['images']} images encoded, "
            f"{t['skipped_images']} undecodable skipped, "
            f"{t['skipped_members']} unreadable members, "
            f"{t['nonfinite_images']} non-finite excluded, "
            f"{t['retries']} retries"
        )


def atomic_save_npy(path: str, arr: np.ndarray) -> None:
    """Write ``path`` via tmp + fsync + ``os.replace`` so a crash
    mid-write never leaves a partial ``.npy``, a re-run (idempotent retry
    / resume) replaces rather than appends, and the bytes are durable
    BEFORE the shard's journal marker commits (the marker vouches for
    these files — without the fsync a power loss could keep the marker
    and lose the features). The per-file DIRECTORY fsync is skipped —
    one ``sync_features`` directory fsync per shard, issued right before
    the journal commit, makes all the renames durable at a thousandth of
    the syscall cost on NFS/FUSE."""
    atomic_write(path, lambda f: np.save(f, arr), mode="wb",
                 sync_dir=False)


# --------------------------------------------------------- shard executor
@dataclasses.dataclass
class _ShardTask:
    index: int
    path: str
    category: int
    attempt: int = 0
    causes: List[dict] = dataclasses.field(default_factory=list)


class _LoadBox:
    """Result slot for one shard-load attempt running on a daemon thread.
    Daemon so a wedged NFS/FUSE read (the hadoop fs -get replacement
    path) parks the thread instead of blocking interpreter exit.
    ``progress`` is a monotone heartbeat the loader bumps per tar member:
    the executor's timeout measures STALL (no heartbeat for
    ``shard_timeout`` seconds), not total load time, so a big-but-healthy
    shard that simply takes a while never gets quarantined — only a read
    that stops making progress does."""

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.progress = 0


def _spawn_load(task: _ShardTask, loader: Callable, image_size: int) -> _LoadBox:
    box = _LoadBox()
    box.t0 = time.perf_counter()  # attempt-span anchor (obs tracing)

    def run():
        try:
            with faults.shard_scope(task.index, task.attempt):
                box.value = loader(task.path, image_size, box)
        except BaseException as e:  # noqa: BLE001 — classified by the caller
            box.error = e
        finally:
            box.event.set()

    t = threading.Thread(
        target=run,
        daemon=True,
        name=f"shard-load-{task.index}-a{task.attempt}",
    )
    t.start()
    return box


def _wait_or_stall(box: _LoadBox, stall_timeout: Optional[float]) -> bool:
    """Wait for the load to finish; False when it went ``stall_timeout``
    seconds without either finishing or advancing its progress heartbeat."""
    if stall_timeout is None:
        box.event.wait()
        return True
    seen = box.progress
    while True:
        if box.event.wait(stall_timeout):
            return True
        if box.progress == seen:
            return False
        seen = box.progress


def _load_shard_python(path: str, image_size: int, box: _LoadBox):
    """Load one shard via the Python tarfile path: [(name, img)], counts.

    The whole decoded shard is materialized (like the seed's load_shard)
    so the executor's retry/journal unit is the shard; peak memory is
    ~(feeder_threads + 1) decoded shards — ``feeder_threads`` is the
    memory lever."""
    faults.fire("tar.open")
    counts = {"skipped_members": 0, "skipped_images": 0}

    def beat():
        box.progress += 1

    images = list(
        iter_tar_images(path, image_size, counts=counts, heartbeat=beat)
    )
    return images, counts


def _load_shard_native(path: str, image_size: int, box: _LoadBox):
    """Load one shard via the native C++ IO runtime (native/tmr_io.cc).
    One stream per shard keeps retry/timeout/journal semantics shard-
    scoped; cross-shard overlap comes from the executor running
    ``feeder_threads`` such streams concurrently. Like the Python loader
    (and unlike the old batch-streaming native path) this holds one
    decoded shard in memory — the price of a shard-scoped fault unit.
    Error granularity is the whole shard: the C++ parser flags an
    unreadable STREAM (-> retry/quarantine, like tarfile.open raising),
    it does not classify individual members, so ``skipped_members`` stays
    0 on this path."""
    from tmr_tpu.data.native_io import NativeTarStream

    faults.fire("tar.open")
    if not os.path.isfile(path):
        raise FileNotFoundError(path)  # non-retryable, like the py path
    counts = {"skipped_members": 0, "skipped_images": 0}
    images = []
    with NativeTarStream([path], threads=1) as stream:
        for _, member, data in stream:
            box.progress += 1
            if not member.lower().endswith((".png", ".jpg", ".jpeg")):
                continue
            faults.fire("tar.member")
            data = faults.corrupt_bytes("tar.member", data)
            img = preprocess_image(data, image_size)
            if img is None:
                counts["skipped_images"] += 1
                continue
            images.append((member, img))
        if stream.errors:
            # the C++ parser flags structural corruption — deterministic,
            # so raise the same non-retryable class as tarfile would
            raise tarfile.ReadError(f"native IO: unreadable shard {path}")
    return images, counts


def _encode_shard(
    task: _ShardTask,
    images,
    encode_stats_fn: Callable,
    batch_size: int,
    save_features,
):
    """Encode one loaded shard: (5,) float64 stat sums, non-finite count.

    Per-image stats that come back non-finite (real encoder overflow or an
    injected NaN poison) are excluded from the sums AND from the feature
    dumps — mirroring the skip-nonfinite step of train/state.py — and
    counted instead of silently averaged in."""
    shard_base = os.path.basename(task.path)
    sums = np.zeros(len(STAT_NAMES), np.float64)
    nonfinite = 0
    with faults.shard_scope(task.index, task.attempt):
        for i in range(0, len(images), batch_size):
            chunk = images[i : i + batch_size]
            names = [n for n, _ in chunk]
            arr = np.stack([im for _, im in chunk])
            real = len(arr)
            if real < batch_size:  # pad to the jitted batch shape
                pad = np.zeros(
                    (batch_size - real,) + arr.shape[1:], arr.dtype
                )
                arr = np.concatenate([arr, pad])
            faults.fire("encode")
            feats, stats = encode_stats_fn(jnp.asarray(arr))
            feats = np.asarray(feats)[:real]
            stats = np.asarray(stats)[:real]
            feats, stats = faults.poison("encode", feats, stats)
            finite = np.isfinite(stats).all(axis=1)
            nonfinite += int((~finite).sum())
            sums[:4] += stats[finite].sum(axis=0)
            sums[4] += int(finite.sum())
            if save_features is not None:
                for name, feat, keep in zip(names, feats, finite):
                    if not keep:
                        continue
                    faults.fire("save")
                    save_features(shard_base, name, feat)
    return sums, nonfinite


def _cleanup_quarantined(task, cleanup_features, log_warning) -> None:
    """A quarantined shard contributed nothing to the table — its
    partially-written (atomic, but orphaned) feature files must not
    linger and break the report/table/files reconciliation."""
    if cleanup_features is None:
        return
    try:
        cleanup_features(os.path.basename(task.path))
    except Exception as e:
        log_warning(
            f"could not clean quarantined shard features for "
            f"{os.path.basename(task.path)}: {e}"
        )


def _run_stream_impl(
    shard_paths: Sequence[str],
    encode_stats_fn: Callable,
    batch_size: int,
    image_size: int,
    save_features,
    feeder_threads: int,
    loader: Callable,
    retry: Optional[RetryPolicy],
    journal,
    resume: bool,
    report: Optional[MapReport],
    cleanup_features=None,
    sync_features=None,
) -> StatAccumulator:
    from tmr_tpu.utils.profiling import log_progress, log_warning

    retry = retry or RetryPolicy()
    if journal is not None:
        # journal markers are keyed on the shard's marker stem (stable
        # across --data_dir spellings between a crash and its resume) —
        # two paths sharing a stem would silently share one done-marker,
        # so refuse up front instead of corrupting the resume ledger
        from collections import Counter

        from tmr_tpu.parallel.journal import shard_stem

        dupes = [n for n, c in Counter(
            shard_stem(p) for p in shard_paths
        ).items() if c > 1]
        if dupes:
            raise ValueError(
                f"duplicate shard journal keys {dupes!r} cannot be "
                "journaled unambiguously; rename the shards or disable "
                "the journal"
            )
    acc = StatAccumulator()
    # (index, category, sums) per completed shard — folded into the table
    # at the END in shard-list order, so a resumed run performs the exact
    # float64 addition sequence of a fault-free run even when the
    # journaled shards are not a prefix (float addition is not
    # associative; byte-identical tables need identical order)
    contributions: List[tuple] = []

    live: List[_ShardTask] = []
    for index, path in enumerate(shard_paths):
        task = _ShardTask(index, path, category_of(path))
        entry = journal.done(os.path.basename(path)) if (
            journal is not None and resume
        ) else None
        if entry is not None:
            obs.get_registry().counter("map.shards_resumed").inc()
            contributions.append((index, entry["category"], entry["sums"]))
            log_progress(
                f"shard {os.path.basename(path)}: resumed from journal "
                f"({entry['images']} images)"
            )
            if report is not None:
                report.add({
                    "index": index,
                    "shard": os.path.basename(path),
                    "category": CATEGORIES[entry["category"]],
                    "status": "resumed",
                    "attempts": 0,
                    "causes": [],
                    "images": entry["images"],
                    "skipped_images": entry["skipped_images"],
                    "skipped_members": entry.get("skipped_members", 0),
                    "nonfinite_images": entry["nonfinite_images"],
                    "wall_s": 0.0,
                })
            continue
        live.append(task)

    pending = deque(live)
    inflight: deque = deque()

    def launch_next() -> None:
        if pending:
            task = pending.popleft()
            inflight.append((task, _spawn_load(task, loader, image_size)))

    for _ in range(max(feeder_threads, 1) + 1):
        launch_next()

    # Shards are PROCESSED strictly in list order (FIFO pop + inline
    # retry), on purpose: a retrying/hung head does stall encoding of
    # later already-loaded shards, but in-order completion is what keeps
    # crash semantics deterministic — the journal is always a prefix of
    # the shard list (minus quarantines), so "resume re-does only
    # in-flight work" is an exact statement rather than a race.
    reg = obs.get_registry()
    while inflight:
        task, box = inflight.popleft()
        t_start = time.monotonic()
        status = "quarantined"
        sums = None
        counts = {"skipped_members": 0, "skipped_images": 0}
        nonfinite = 0
        n_images = 0
        shard_base = os.path.basename(task.path)
        while True:
            failure: Optional[dict] = None
            if not _wait_or_stall(box, retry.shard_timeout):
                failure = {
                    "attempt": task.attempt,
                    "cause": "timeout",
                    "error": (
                        f"shard load stalled: no progress for "
                        f"{retry.shard_timeout}s"
                    ),
                }
                # the stalled window as a span: load start -> stall verdict
                obs.add_span("map.stall", box.t0, time.perf_counter(),
                             shard=shard_base, attempt=task.attempt)
            elif box.error is not None:
                err = box.error
                if isinstance(err, (KeyboardInterrupt, SystemExit)):
                    raise err  # a crash is a crash — resume handles it
                failure = {
                    "attempt": task.attempt,
                    "cause": "exception",
                    "error": f"{type(err).__name__}: {err}",
                }
                if isinstance(err, _NON_RETRYABLE):
                    failure["retryable"] = False
            else:
                images, counts = box.value
                log_progress(
                    f"shard {os.path.basename(task.path)}: "
                    f"{len(images)} images ({CATEGORIES[task.category]}, "
                    f"attempt {task.attempt + 1})"
                )
                try:
                    with obs.span("map.encode", shard=shard_base,
                                  attempt=task.attempt):
                        sums, nonfinite = _encode_shard(
                            task, images, encode_stats_fn, batch_size,
                            save_features,
                        )
                    n_images = int(sums[4])
                    if journal is not None:
                        if sync_features is not None:
                            # ONE directory fsync per shard makes every
                            # feature rename durable before the marker
                            # that vouches for them commits
                            sync_features(os.path.basename(task.path))
                        with faults.shard_scope(task.index, task.attempt):
                            journal.record(
                                os.path.basename(task.path),
                                category=task.category,
                                sums=sums,
                                images=n_images,
                                skipped_images=counts["skipped_images"],
                                skipped_members=counts["skipped_members"],
                                nonfinite_images=nonfinite,
                                attempts=task.attempt + 1,
                                wall_s=time.monotonic() - t_start,
                            )
                    status = "ok"
                    obs.add_span("map.attempt", box.t0,
                                 time.perf_counter(), shard=shard_base,
                                 attempt=task.attempt, status="ok")
                    break
                except Exception as e:
                    failure = {
                        "attempt": task.attempt,
                        "cause": "exception",
                        "error": f"{type(e).__name__}: {e}",
                    }
                    if isinstance(e, _NON_RETRYABLE):
                        # the encode/save/journal half hits permanent
                        # errors too (features_out on an unmounted volume)
                        failure["retryable"] = False

            obs.add_span("map.attempt", box.t0, time.perf_counter(),
                         shard=shard_base, attempt=task.attempt,
                         status=failure["cause"])
            task.causes.append(failure)
            task.attempt += 1
            retryable = failure.pop("retryable", True)
            if task.attempt >= retry.max_attempts or not retryable:
                log_warning(
                    f"quarantining shard {os.path.basename(task.path)} "
                    f"after {task.attempt} attempt(s): {failure['error']}"
                )
                break
            reg.counter("map.retries").inc()
            with obs.span("map.backoff", shard=shard_base,
                          attempt=task.attempt):
                time.sleep(retry.delay(task.index, task.attempt))
            box = _spawn_load(task, loader, image_size)

        wall = time.monotonic() - t_start
        reg.counter("map.shards_ok" if status == "ok"
                    else "map.shards_quarantined").inc()
        reg.histogram("map.shard_wall_s").observe(wall)
        if obs.flight_enabled():  # one bool check when off
            obs.flight_record(
                "map.shard", shard=shard_base, status=status,
                attempts=task.attempt + 1, images=n_images,
                nonfinite_images=nonfinite, wall_s=round(wall, 6),
            )
        if status == "ok":
            reg.counter("map.images").inc(n_images)
            reg.counter("map.nonfinite_images").inc(nonfinite)
            contributions.append((task.index, task.category, sums))
        elif status == "quarantined":
            if journal is not None:
                # a marker from an EARLIER successful run must not vouch
                # for features this quarantine just invalidated/cleaned
                journal.invalidate(os.path.basename(task.path))
            _cleanup_quarantined(task, cleanup_features, log_warning)
        launch_next()
        if report is not None:
            # a quarantined shard contributed nothing to the table, even
            # if a late attempt got through load/encode before failing —
            # report zeros for every per-image counter so the totals
            # reconcile with the table's count column
            ok = status == "ok"
            report.add({
                "index": task.index,
                "shard": os.path.basename(task.path),
                "category": CATEGORIES[task.category],
                "status": status,
                "attempts": task.attempt + (1 if ok else 0),
                "causes": task.causes,
                "images": n_images if ok else 0,
                "skipped_images": counts["skipped_images"] if ok else 0,
                "skipped_members": counts["skipped_members"] if ok else 0,
                "nonfinite_images": nonfinite if ok else 0,
                "wall_s": time.monotonic() - t_start,
            })
    # one float64 addition per shard, in shard-list order — the
    # resume-equivalence unit
    for _, category, sums in sorted(contributions, key=lambda c: c[0]):
        acc.add_totals(category, sums)
    return acc


def run_stream(
    shard_paths: Sequence[str],
    encode_stats_fn: Callable,
    batch_size: int = 8,
    image_size: int = 1024,
    save_features: Optional[Callable[[str, str, np.ndarray], None]] = None,
    feeder_threads: int = 4,
    *,
    retry: Optional[RetryPolicy] = None,
    journal=None,
    resume: bool = False,
    report: Optional[MapReport] = None,
    cleanup_features=None,
    sync_features=None,
) -> StatAccumulator:
    """Single-host streaming map phase over tar shards.

    Host feeder threads decode shards ahead of the device; the device runs
    the jitted encoder on fixed-size batches (short tails padded and
    masked out of the stats). ``save_features(shard, image_name, features)``
    is the .npy side-effect hook (mapper.py:117-118).

    Fault tolerance: shards run under ``retry`` (RetryPolicy — attempt
    loop, backoff, per-shard stall timeout, quarantine); ``journal``
    (journal.ShardJournal) records per-shard done-markers and
    ``resume=True`` skips journaled shards; ``report`` (MapReport)
    collects the map_report/v1 record per shard;
    ``cleanup_features(shard_base)`` is invoked for quarantined shards so
    partially-written feature files don't outlive their exclusion from
    the table (their journal marker, if any, is invalidated too);
    ``sync_features(shard_base)`` is invoked once per shard right before
    its journal commit to fsync the feature directory. Peak memory is
    ~(feeder_threads + 1) decoded shards.
    """
    return _run_stream_impl(
        shard_paths, encode_stats_fn, batch_size, image_size,
        save_features, feeder_threads, _load_shard_python, retry, journal,
        resume, report, cleanup_features, sync_features,
    )


def run_stream_native(
    shard_paths: Sequence[str],
    encode_stats_fn: Callable,
    batch_size: int = 8,
    image_size: int = 1024,
    save_features: Optional[Callable[[str, str, np.ndarray], None]] = None,
    feeder_threads: int = 4,
    *,
    retry: Optional[RetryPolicy] = None,
    journal=None,
    resume: bool = False,
    report: Optional[MapReport] = None,
    cleanup_features=None,
    sync_features=None,
) -> StatAccumulator:
    """run_stream on the native C++ IO runtime (native/tmr_io.cc): tar
    parsing happens in C++ outside the GIL; Python only decodes images and
    feeds the device. Each shard gets its own single-thread stream so the
    retry/timeout/journal unit stays the shard (cross-shard overlap comes
    from ``feeder_threads`` concurrent streams), with semantics — and the
    stats table — identical to the Python path."""
    return _run_stream_impl(
        shard_paths, encode_stats_fn, batch_size, image_size,
        save_features, feeder_threads, _load_shard_native, retry, journal,
        resume, report, cleanup_features, sync_features,
    )


def allreduce_stats(table: jnp.ndarray, axis_name: str = "data") -> jnp.ndarray:
    """The shuffle replacement: psum per-device (4, 5) partials over the
    mesh axis. Use inside shard_map/pmap; see tests/test_parallel.py."""
    return jax.lax.psum(table, axis_name)


# --------------------------------------------------------------------- CLI
# Hadoop-Streaming-compatible entry points:
#   cat list_tars.txt | python -m tmr_tpu.parallel.mapreduce map \
#       --data_dir /data/tars --artifact exported/encoder.stablehlo \
#       --features_out features_output \
#   | sort | python -m tmr_tpu.parallel.mapreduce reduce
# The map phase reads tar names from stdin (mapper.py:51), prefixes
# --data_dir (the `hadoop fs -get` replacement: a posix/NFS/FUSE path),
# streams every shard through the jitted encoder, writes per-image feature
# .npy files ATOMICALLY (tmp + os.replace) under features_out/<category>/
# (mapper.py:126-130), and emits aggregated `category\tsums,count` records
# (mapper.py:138; aggregated per-run rather than per-tar — reduce
# semantics are identical since the reducer sums). The reduce phase needs
# no sort (dict aggregation) but tolerates sorted Hadoop-style streams
# identically.
#
# Fault tolerance knobs (the Hadoop JobTracker replacement):
#   --max_attempts N     per-shard tries before quarantine (default 3)
#   --shard_timeout S    per-attempt STALL budget for the shard load — no
#                        member progress for S seconds fails the attempt
#                        (hung NFS/FUSE protection that never quarantines a
#                        merely-slow shard; 0 disables; default 600)
#   --backoff_base S / --backoff_max S
#                        capped exponential retry backoff with
#                        deterministic jitter (backoff_delay)
#   --resume             skip shards with a valid journal done-marker,
#                        folding their journaled sums into the table
#                        (byte-identical to a fault-free run)
#   --journal_dir DIR    done-marker directory (default
#                        <features_out>/_journal when --features_out set)
#   --report_out FILE    write the map_report/v1 document: per-shard
#                        status/attempts/causes, quarantined + resumed
#                        lists, skipped-image / non-finite counts, retry
#                        totals, wall-clock per shard (schema registered
#                        in tmr_tpu/diagnostics.py:MAP_REPORT_SCHEMA)
# Deterministic fault injection for drills/tests: set TMR_FAULTS (and
# TMR_FAULTS_SEED), e.g.
#   TMR_FAULTS="tar.open:shard=3:attempts=2:raise=OSError;encode:shard=7:latency=30"
# — see tmr_tpu/utils/faults.py for the schedule grammar and
# scripts/chaos_probe.py for the canned gauntlet.


def _cli_map(args) -> int:
    import sys

    from tmr_tpu.parallel.journal import ShardJournal
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        # loud on purpose: a TMR_FAULTS left over from a drill would
        # otherwise corrupt a production run that still exits 0
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )

    names = [ln.strip() for ln in sys.stdin if ln.strip()]
    paths = [
        n if os.path.isabs(n) else os.path.join(args.data_dir, n)
        for n in names
    ]
    log_info(f"map: {len(paths)} shards from stdin")

    if args.artifact:
        fn = make_encode_stats_fn_from_artifact(args.artifact)
    else:
        from tmr_tpu.models import build_sam_encoder

        if not args.checkpoint:
            log_warning("map: no --artifact/--checkpoint, random weights")
        model, params = build_sam_encoder(
            args.model_type, args.checkpoint, args.image_size
        )
        fn = make_encode_stats_fn(model, params)

    # ONE definition of the features_out/<category>/<shard>/ layout for
    # this CLI and the elastic workers — the byte-identical-tree parity
    # chaos_probe asserts depends on the two paths never drifting
    from tmr_tpu.parallel.elastic import make_feature_sinks

    save, cleanup, sync = make_feature_sinks(args.features_out)

    journal_dir = args.journal_dir
    if journal_dir is None and args.features_out:
        journal_dir = os.path.join(args.features_out, "_journal")
    journal = ShardJournal(journal_dir) if journal_dir else None
    if args.resume and journal is None:
        log_warning(
            "map: --resume without --journal_dir/--features_out has no "
            "journal to resume from; running everything"
        )

    retry = RetryPolicy(
        max_attempts=max(1, args.max_attempts),
        shard_timeout=args.shard_timeout if args.shard_timeout > 0 else None,
        backoff_base=args.backoff_base,
        backoff_max=args.backoff_max,
    )
    report = MapReport()

    use_native = not args.no_native
    if use_native:
        from tmr_tpu.data import native_io

        use_native = native_io.available()
        if not use_native:
            log_info("native IO unavailable; using the Python tarfile path")
    runner = run_stream_native if use_native else run_stream
    acc = runner(
        paths, fn, batch_size=args.batch_size, image_size=args.image_size,
        save_features=save, feeder_threads=args.feeder_threads,
        retry=retry, journal=journal, resume=args.resume, report=report,
        cleanup_features=cleanup,
        sync_features=sync,
    )
    log_info(report.summary_line())
    if args.report_out:
        report.write(args.report_out)
    for line in acc.emit_lines():
        # stdout IS the Hadoop-streaming record protocol here; explicit
        # writes keep the tier-1 stdout-hygiene lint's meaning (no bare
        # print) without touching the record format
        sys.stdout.write(line + "\n")
    sys.stdout.flush()
    return 0


def _cli_reduce(_args) -> int:
    import sys

    sums = reduce_lines(sys.stdin)
    sys.stdout.write(format_stats_table(sums) + "\n")
    sys.stdout.flush()
    return 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tmr_tpu.parallel.mapreduce",
        description="Streaming feature extraction (Hadoop mapper/reducer "
                    "replacement)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("map", help="tar names on stdin -> stat records")
    m.add_argument("--data_dir", default=".",
                   help="prefix for shard names (the HDFS tar directory)")
    m.add_argument("--artifact", default=None,
                   help="serialized encoder from export_encoder.py")
    m.add_argument("--checkpoint", default=None)
    m.add_argument("--model_type", default="vit_b")
    m.add_argument("--features_out", default=None,
                   help="write per-image feature .npy under "
                        "<dir>/<category>/<shard>/ (mapper.py:126-130)")
    m.add_argument("--batch_size", default=8, type=int)
    m.add_argument("--image_size", default=1024, type=int)
    m.add_argument("--feeder_threads", default=4, type=int)
    m.add_argument("--no_native", action="store_true",
                   help="force the Python tarfile path instead of the C++ "
                        "IO runtime (native/tmr_io.cc)")
    m.add_argument("--max_attempts", default=3, type=int,
                   help="per-shard attempts before quarantine")
    m.add_argument("--shard_timeout", default=600.0, type=float,
                   help="per-attempt STALL budget (s): quarantine-path "
                        "timeout fires only when the shard load makes no "
                        "member progress for this long; 0 disables")
    m.add_argument("--backoff_base", default=0.5, type=float,
                   help="first-retry backoff (s), doubled per retry")
    m.add_argument("--backoff_max", default=30.0, type=float,
                   help="backoff cap (s)")
    m.add_argument("--resume", action="store_true",
                   help="skip shards journaled as done; their journaled "
                        "sums keep the stats table byte-identical")
    m.add_argument("--journal_dir", default=None,
                   help="done-marker directory (default "
                        "<features_out>/_journal)")
    m.add_argument("--report_out", default=None,
                   help="write the map_report/v1 JSON document here")
    sub.add_parser("reduce", help="stat records on stdin -> averages table")
    args = p.parse_args(argv)
    return _cli_map(args) if args.cmd == "map" else _cli_reduce(args)


if __name__ == "__main__":
    raise SystemExit(main())
