"""jax-version compat for the sharding surface (the
``_tpu_compiler_params`` situation applied to ``shard_map``).

jax moved ``shard_map`` out of ``jax.experimental`` into the top-level
namespace and renamed its replication-check kwarg ``check_rep`` ->
``check_vma`` along the way. The parallel modules and their tests target
the new spelling; on a 0.4.x runtime the top-level import fails and the
new kwarg is unknown — which is exactly how tests/test_ring.py carried a
collection error from the seed until this shim. One definition here so
every caller (ring, pipeline, xcorr's data island, the tests) resolves
the API the same way on every installed jax.
"""

from __future__ import annotations

try:  # jax >= 0.6: the supported top-level export
    from jax import shard_map as _shard_map

    _NEW_API = True
except ImportError:  # jax 0.4.x/0.5.x: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the new-API signature on every jax.

    ``check_vma`` maps onto the old API's ``check_rep`` (same meaning,
    renamed): both toggle the static replication/varying-manual-axes
    check that several of our islands disable (collectives whose
    replication the checker cannot prove).
    """
    kw = {"check_vma" if _NEW_API else "check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def compile_sharded(f, mesh, *, in_shardings=None, out_shardings=None,
                    in_specs=None, out_specs=None, donate_argnums=()):
    """One compile seam for the sharded serving programs (the SNIPPETS.md
    compile-helper pattern): explicit shardings -> ``jax.jit`` with
    ``in_shardings``/``out_shardings`` (the pjit/GSPMD path — XLA derives
    the tensor-parallel collectives from the param specs), plain
    PartitionSpecs -> :func:`shard_map` over the mesh wrapped in jit (the
    pure data-parallel map path, whose per-shard trace IS the unsharded
    program body — the serving tier's bitwise-exactness lever).

    Exactly one of the two spec families must be given; mixing them is a
    caller bug, refused loudly.
    """
    import jax

    use_pjit = in_shardings is not None or out_shardings is not None
    use_smap = in_specs is not None or out_specs is not None
    if use_pjit == use_smap:
        raise ValueError(
            "compile_sharded: pass in_shardings/out_shardings (pjit) OR "
            "in_specs/out_specs (shard_map), not both/neither"
        )
    if use_pjit:
        if in_shardings is None or out_shardings is None:
            raise ValueError(
                "compile_sharded: the pjit path needs BOTH in_shardings "
                "and out_shardings"
            )
        return jax.jit(f, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=donate_argnums)
    if in_specs is None or out_specs is None:
        raise ValueError(
            "compile_sharded: the shard_map path needs BOTH in_specs "
            "and out_specs"
        )
    return jax.jit(
        shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False),
        donate_argnums=donate_argnums,
    )
