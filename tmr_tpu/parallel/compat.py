"""jax-version compat for the sharding surface (the
``_tpu_compiler_params`` situation applied to ``shard_map``).

jax moved ``shard_map`` out of ``jax.experimental`` into the top-level
namespace and renamed its replication-check kwarg ``check_rep`` ->
``check_vma`` along the way. The parallel modules and their tests target
the new spelling; on a 0.4.x runtime the top-level import fails and the
new kwarg is unknown — which is exactly how tests/test_ring.py carried a
collection error from the seed until this shim. One definition here so
every caller (ring, pipeline, xcorr's data island, the tests) resolves
the API the same way on every installed jax.
"""

from __future__ import annotations

try:  # jax >= 0.6: the supported top-level export
    from jax import shard_map as _shard_map

    _NEW_API = True
except ImportError:  # jax 0.4.x/0.5.x: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the new-API signature on every jax.

    ``check_vma`` maps onto the old API's ``check_rep`` (same meaning,
    renamed): both toggle the static replication/varying-manual-axes
    check that several of our islands disable (collectives whose
    replication the checker cannot prove).
    """
    kw = {"check_vma" if _NEW_API else "check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)
