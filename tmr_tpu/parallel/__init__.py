"""Device-mesh parallelism.

The TPU replacement for BOTH of the reference's distribution mechanisms
(SURVEY.md §2.2-2.3):

- Lightning DDP/NCCL training (reference main.py:111-112) ->
  ``jax.sharding`` data parallelism over the mesh 'data' axis; gradient
  psum is inserted by XLA from the sharding annotations.
- Hadoop Streaming mapper/reducer inference (mapper.py/reducer.py) ->
  sharded streaming in parallel/mapreduce.py: each device owns a shard
  stream, the sort/shuffle collapses into an on-device reduction of
  fixed-size stat tuples.

Mesh axes: ('data', 'model') — plus an optional 'seq' axis for
sequence/context parallelism and a 'pipe' axis for pipeline parallelism.
'model' tensor-parallelism shards the ViT attention/MLP feature dims; 'seq'
runs the global-attention blocks as ring attention over token-row bands
(parallel/ring.py); 'pipe' streams microbatches through stage-sharded
encoder blocks with a GPipe schedule (parallel/pipeline.py). None are
required for reference parity (the reference has only DDP) but all are
first-class here for scaling ViT-H and long token grids beyond one chip.
"""

from tmr_tpu.parallel.journal import ShardJournal  # noqa: F401
from tmr_tpu.parallel.mesh import make_mesh  # noqa: F401
from tmr_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_vit_apply,
    stack_stage_params,
    stage_sharding,
    stage_split,
)
from tmr_tpu.parallel.ring import (  # noqa: F401
    dense_attention,
    make_ring_attention_fn,
    ring_attention,
    ring_decomposed_attention,
    ulysses_attention,
)
from tmr_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_spec,
    shard_params,
    state_sharding,
)
