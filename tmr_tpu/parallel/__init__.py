"""Device-mesh parallelism.

The TPU replacement for BOTH of the reference's distribution mechanisms
(SURVEY.md §2.2-2.3):

- Lightning DDP/NCCL training (reference main.py:111-112) ->
  ``jax.sharding`` data parallelism over the mesh 'data' axis; gradient
  psum is inserted by XLA from the sharding annotations.
- Hadoop Streaming mapper/reducer inference (mapper.py/reducer.py) ->
  sharded streaming in parallel/mapreduce.py: each device owns a shard
  stream, the sort/shuffle collapses into an on-device reduction of
  fixed-size stat tuples.

Mesh axes: ('data', 'model') — plus an optional 'seq' axis for
sequence/context parallelism. 'model' tensor-parallelism shards the ViT
attention/MLP feature dims; 'seq' runs the global-attention blocks as ring
attention over token-row bands (parallel/ring.py). Neither is required for
reference parity (the reference has no TP/SP) but both are first-class here
for scaling ViT-H and long token grids beyond one chip.
"""

from tmr_tpu.parallel.mesh import make_mesh  # noqa: F401
from tmr_tpu.parallel.ring import (  # noqa: F401
    dense_attention,
    make_ring_attention_fn,
    ring_attention,
    ring_decomposed_attention,
    ulysses_attention,
)
from tmr_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_spec,
    shard_params,
    state_sharding,
)
