"""Generic lease service: typed resources, epoch-fenced grants,
heartbeat liveness, and cause-tagged reassignment.

PR 10 built this machinery for map shards inside ``ElasticCoordinator``;
this module is that state machine extracted so OTHER resource kinds can
ride it — the serve fleet (serve/fleet.py) leases **traffic partitions**
with exactly the shard semantics: a monotone per-resource epoch fences
every commit, a stale heartbeat revokes, a dead worker's holdings
reassign under epoch+1, a worker failing too many distinct resources is
drained. The map-shard coordinator remains the first client
(parallel/elastic.py) with byte-identical behavior — same counters, same
reassignment records, same grant discipline — pinned by the existing
``--elastic`` chaos gauntlet.

Design notes:

- **one RLock** guards all mutable run state. Re-entrant on purpose:
  clients compose multi-step transitions (fence-check + client-specific
  bookkeeping + commit) under ``with service.lock:`` while every public
  method still takes the lock itself, so no caller can touch state
  unlocked by accident.
- **two-phase grants**: ``select()`` reserves (resource, epoch) under
  the lock; the client fires its fault point / does I/O OUTSIDE the
  lock; ``install()`` or ``requeue()`` completes or aborts the grant.
  Same for straggler election (``elect_straggler`` →
  ``confirm_steal``/``veto_steal``). Latency injected at those points
  must never stall every other worker's heartbeat.
- **transition hook**: ``on_transition(resource, lease, state)`` fires
  under the lock at held/revoked/committed/failed — the map client
  writes its durable ``_leases/*.json`` record there, the fleet client
  queues rebalance events for its router thread.
- **metric names** are client-shaped (``metrics_prefix``/``noun``) so
  the elastic counters (``elastic.shards_committed``, ...) did not move.

Resources need not ever settle: the fleet's partitions are leased for
the lifetime of their holder and simply re-enter the pending queue on
revocation — ``wait()``/``done`` only matter to clients whose resources
commit (map shards).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tmr_tpu import obs

#: closed reassignment-cause vocabulary (mirrored by
#: diagnostics.ELASTIC_REASSIGN_CAUSES, which validators consume):
#: stale_heartbeat | worker_exit | straggler | poison_worker | scale_out
REASSIGN_CAUSES = (
    "stale_heartbeat", "worker_exit", "straggler", "poison_worker",
    "scale_out",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class LeasePolicy:
    """Liveness / straggler / poison knobs for one lease service.

    ``lease_ttl_s`` is the heartbeat budget: a lease not heartbeated for
    this long is revoked and its resource reassigned. ``hb_interval_s``
    is the worker's beat cadence (default TTL/4 so one dropped beat
    never revokes). ``straggler_factor`` scales the rolling median of
    committed resource wall times into the speculative-re-execution
    bound (0 disables); ``straggler_min_done`` committed resources are
    required before the median means anything. ``max_reassigns`` bounds
    how many times one resource may bounce before it is quarantined
    outright; ``poison_failures`` distinct failed resources drain a
    worker; ``resource_fail_workers`` distinct workers failing one
    resource quarantine the resource."""

    lease_ttl_s: float = 10.0
    hb_interval_s: float = 2.5
    check_interval_s: float = 1.0
    straggler_factor: float = 3.0
    straggler_min_s: float = 5.0
    straggler_min_done: int = 3
    max_reassigns: int = 4
    poison_failures: int = 3
    resource_fail_workers: int = 2

    @classmethod
    def from_env(cls, **overrides) -> "LeasePolicy":
        """Resolve defaults from the TMR_ELASTIC_* env knobs (read
        lazily, at call time — the one lease-liveness knob family both
        clients share), then apply explicit overrides."""
        ttl = _env_float("TMR_ELASTIC_TTL_S", 10.0)
        base = dict(
            lease_ttl_s=ttl,
            hb_interval_s=_env_float("TMR_ELASTIC_HB_S", ttl / 4.0),
            check_interval_s=_env_float("TMR_ELASTIC_CHECK_S", ttl / 10.0),
            straggler_factor=_env_float("TMR_ELASTIC_STRAGGLER_FACTOR",
                                        3.0),
            straggler_min_s=_env_float("TMR_ELASTIC_STRAGGLER_MIN_S", 5.0),
            max_reassigns=_env_int("TMR_ELASTIC_MAX_REASSIGNS", 4),
            poison_failures=_env_int("TMR_ELASTIC_POISON_FAILURES", 3),
        )
        base.update(overrides)
        return cls(**base)


class Lease:
    __slots__ = ("worker", "epoch", "granted_at", "expires_at", "hb")

    def __init__(self, worker: str, epoch: int, granted_at: float,
                 ttl_s: float):
        self.worker = worker
        self.epoch = epoch
        self.granted_at = granted_at
        self.expires_at = granted_at + ttl_s
        self.hb = 0


class Resource:
    """One leasable resource. ``key`` is the durable identity carried in
    reassignment/fence records (a shard basename, a partition name);
    clients subclass to attach their own payload fields (the map shard
    adds path/category/entry, the fleet partition adds routing keys)."""

    __slots__ = (
        "index", "key", "status", "next_epoch", "leases", "assignments",
        "reassigns", "failures", "failed_workers", "worker", "epoch",
        "straggled", "first_granted_at", "wall_s", "cleaned",
    )

    def __init__(self, index: int, key: str):
        self.index = index
        self.key = key
        self.status = "pending"  # pending|leased|committed|resumed|quarantined
        self.next_epoch = 1
        self.leases: Dict[int, Lease] = {}
        self.assignments = 0
        #: reassignment records for THIS resource (stragglers included)
        #: — the O(1) bound counter; the service-level list is the
        #: report's content, never rescanned per event
        self.reassigns = 0
        self.failures: List[dict] = []
        self.failed_workers: set = set()
        self.worker: Optional[str] = None
        self.epoch: Optional[int] = None
        self.straggled = False
        self.first_granted_at: Optional[float] = None
        self.wall_s = 0.0
        self.cleaned = False

    @property
    def settled(self) -> bool:
        return self.status in ("committed", "resumed", "quarantined")


class WorkerRecord:
    __slots__ = ("wid", "committed", "failed", "drained", "dead", "bye")

    def __init__(self, wid: str):
        self.wid = wid
        self.committed = 0
        self.failed: set = set()
        self.drained = False
        self.dead = False
        self.bye = False


class LeaseService:
    """The epoch-fenced lease state machine over a fixed resource list.

    All mutable state lives behind ``self.lock`` (an RLock — see the
    module docstring for the composition contract). Clients provide the
    wire protocol, durable records, and reports; this class provides the
    one correct grant/heartbeat/fence/reassign/drain discipline."""

    def __init__(self, resources: Sequence[Resource],
                 policy: Optional[LeasePolicy] = None, *,
                 metrics_prefix: str = "lease", noun: str = "resource",
                 key_field: str = "resource",
                 on_transition: Optional[Callable] = None,
                 history_bound: Optional[int] = None):
        self.policy = policy or LeasePolicy()
        self.lock = threading.RLock()
        self.resources: List[Resource] = list(resources)
        keys = [r.key for r in self.resources]
        if len(set(keys)) != len(keys):
            raise ValueError(
                f"duplicate {noun} keys cannot be leased unambiguously"
            )
        #: record-key name in reassignment/fence dicts ("shard" for the
        #: map client, "partition" for the fleet)
        self.key_field = key_field
        self._prefix = metrics_prefix
        self._noun = noun
        #: fires under the lock at every lease state change
        #: (resource, lease, "held"|"revoked"|"committed"|"failed")
        self.on_transition = on_transition
        self._pending: deque = deque(
            r.index for r in self.resources if not r.settled
        )
        self.workers: Dict[str, WorkerRecord] = {}
        #: oldest records roll off past ``history_bound`` (None =
        #: unbounded — the map client's report validator reconciles
        #: totals against list LENGTHS, and a map run is bounded by its
        #: shard count anyway; the indefinitely-serving fleet passes a
        #: bound so a flapping worker cannot grow these forever)
        self.history_bound = history_bound
        self.reassignments: List[dict] = []
        self.fenced: List[dict] = []
        self._settled = sum(1 for r in self.resources if r.settled)
        self.done_event = threading.Event()
        self._t0 = time.monotonic()
        self.wall_s = 0.0
        if self._settled == len(self.resources):
            self.done_event.set()

    # ------------------------------------------------------------- counters
    def _count(self, name: str) -> None:
        obs.get_registry().counter(f"{self._prefix}.{name}").inc()

    def _trim_locked(self, records: List[dict]) -> None:
        if self.history_bound and len(records) > self.history_bound:
            del records[:-self.history_bound]

    # -------------------------------------------------------------- workers
    def worker_rec(self, wid: str) -> WorkerRecord:
        with self.lock:
            rec = self.workers.get(wid)
            if rec is None:
                rec = self.workers[wid] = WorkerRecord(wid)
            return rec

    def rejoin(self, wid: str) -> WorkerRecord:
        """A worker re-introduced itself (a fresh ``hello``): clear the
        departure flags a previous incarnation under the same stable id
        left behind — without this, a restarted worker is treated as
        departed forever (its state pruned each pass, its grants
        black-holed). ``drained`` stays STICKY on purpose: a
        poison-drained worker must not re-admit itself by reconnecting.
        """
        with self.lock:
            rec = self.worker_rec(wid)
            rec.dead = False
            rec.bye = False
            return rec

    def live_workers(self) -> List[str]:
        """Worker ids currently eligible for grants: registered, not
        departed (dead/bye), not poison-drained. Replica placement
        (serve/gallery_fleet.py) mirrors pattern payloads onto these."""
        with self.lock:
            return [w.wid for w in self.workers.values()
                    if not (w.dead or w.bye or w.drained)]

    def restart_clock(self) -> None:
        """Re-anchor the run clock (clients call this at ``start()`` so
        reported wall time measures SERVING, not construction — resume
        journal scans and caller setup between construction and start
        must not inflate it)."""
        with self.lock:
            self._t0 = time.monotonic()

    def mark_resumed(self, index: int, worker: Optional[str] = None,
                     epoch: Optional[int] = None) -> None:
        """Settle one resource as resumed (a prior run's durable commit
        was folded in) before any grants happen."""
        with self.lock:
            res = self.resources[index]
            res.status = "resumed"
            res.worker = worker
            res.epoch = epoch
            try:
                self._pending.remove(index)
            except ValueError:
                pass
            self._settle_locked()

    # ---------------------------------------------------------------- grant
    def select(self, wid: str) -> Tuple[str, Optional[Resource], int]:
        """Phase one of a grant: pick a resource for ``wid`` and reserve
        the next epoch. Returns ``(verdict, resource, epoch)`` with
        verdict one of "drained" / "done" / "wait" / "grant" — only
        "grant" carries a resource. The caller fires its fault point
        outside the lock, then calls :meth:`install` (success) or
        :meth:`requeue` (abort)."""
        with self.lock:
            worker = self.worker_rec(wid)
            if worker.drained:
                return ("drained", None, 0)
            if self.done_event.is_set():
                return ("done", None, 0)
            # a worker is not handed back a resource it already failed —
            # UNLESS it is the only non-drained live worker left (the
            # reassignment bound then ends the ping-pong in quarantine).
            # Departed workers (clean bye included) are NOT alive: a
            # sole survivor skipping its failed resource forever would
            # leave the run unsettleable.
            others_alive = any(
                w.wid != wid and not w.drained and not w.dead
                and not w.bye
                for w in self.workers.values()
            )
            # fairness cap: a worker already holding its share of the
            # CONCURRENT leases (ceil(resources / alive workers)) waits
            # while an under-loaded live peer exists — this is what
            # makes a scale-out rebalance deterministic (the freed
            # partition goes to the recruit, not back to the loaded
            # holder that freed it). Map workers hold one lease at a
            # time, so with shards >= workers the cap never binds there
            # (grant behavior unchanged, gauntlet-pinned).
            if others_alive:
                held_per: Dict[str, int] = {}
                for res in self.resources:
                    for lease in res.leases.values():
                        held_per[lease.worker] = (
                            held_per.get(lease.worker, 0) + 1
                        )
                alive = [
                    w.wid for w in self.workers.values()
                    if not (w.drained or w.dead or w.bye)
                ]
                cap = -(-len(self.resources) // max(len(alive), 1))
                if held_per.get(wid, 0) >= cap and any(
                    held_per.get(w, 0) < cap
                    for w in alive if w != wid
                ):
                    return ("wait", None, 0)
            chosen = None
            for _ in range(len(self._pending)):
                idx = self._pending.popleft()
                cand = self.resources[idx]
                if cand.settled:
                    continue  # a straggler dup whose original won
                if wid in cand.failed_workers and others_alive:
                    self._pending.append(idx)  # someone else's to retry
                    continue
                chosen = cand
                break
            if chosen is None:
                return ("wait", None, 0)
            epoch = chosen.next_epoch
            chosen.next_epoch += 1
            return ("grant", chosen, epoch)

    def requeue(self, resource: Resource) -> None:
        """Abort a reserved grant (the fault point vetoed it): put the
        resource back at the FRONT of the queue unless it settled in
        the window."""
        with self.lock:
            if not resource.settled:
                self._pending.appendleft(resource.index)

    def install(self, resource: Resource, epoch: int,
                wid: str) -> Optional[Lease]:
        """Phase two of a grant: install the lease. None when the
        resource settled while the caller was outside the lock (the
        straggler-dup race) — the grant is then void."""
        now = time.monotonic()
        with self.lock:
            if resource.settled:
                return None
            lease = Lease(wid, epoch, now, self.policy.lease_ttl_s)
            resource.leases[epoch] = lease
            resource.status = "leased"
            resource.assignments += 1
            if resource.first_granted_at is None:
                resource.first_granted_at = now
            if self.on_transition is not None:
                self.on_transition(resource, lease, "held")
            self._count("leases_granted")
            return lease

    # ------------------------------------------------------------- liveness
    def current_lease(self, index: int, epoch: int,
                      wid: str) -> Optional[Lease]:
        with self.lock:
            if not (0 <= index < len(self.resources)):
                return None
            res = self.resources[index]
            if res.settled:
                return None
            lease = res.leases.get(epoch)
            if lease is None or lease.worker != wid:
                return None
            return lease

    def heartbeat(self, wid: str, index: int, epoch: int) -> bool:
        """Extend one lease's expiry; False == the epoch is stale (the
        caller should drop its local claim)."""
        with self.lock:
            lease = self.current_lease(index, epoch, wid)
            if lease is None:
                return False
            # expiry extension is memory-only: durable lease records are
            # advisory (rewritten on grant/revoke/commit/fail) and a
            # per-beat disk write under the protocol lock would
            # serialize every worker's beat on disk latency
            lease.expires_at = time.monotonic() + self.policy.lease_ttl_s
            lease.hb += 1
            return True

    def record_fence(self, index: int, wid: str, epoch: int,
                     op: str) -> None:
        """One stale-epoch rejection record (op: precommit|commit)."""
        with self.lock:
            key = (
                self.resources[index].key
                if 0 <= index < len(self.resources) else f"#{index}"
            )
            self.fenced.append({
                self.key_field: key, "index": index, "worker": wid,
                "epoch": epoch, "op": op,
            })
            self._trim_locked(self.fenced)
            self._count("fenced_rejections")

    # ------------------------------------------------------------ terminals
    def commit(self, wid: str, index: int,
               epoch: int) -> Optional[Tuple[Resource, Lease]]:
        """Fence-checked commit. None == stale (a fence record was
        written; the client decides what to do about any durable marker
        the loser slipped to disk). On success the resource is settled
        under (wid, epoch) and every outstanding lease on it cleared;
        the client fills its payload fields under the same lock hold."""
        with self.lock:
            lease = self.current_lease(index, epoch, wid)
            if lease is None:
                self.record_fence(index, wid, epoch, "commit")
                return None
            res = self.resources[index]
            res.status = "committed"
            res.worker = wid
            res.epoch = epoch
            res.wall_s = time.monotonic() - (
                res.first_granted_at or lease.granted_at
            )
            if self.on_transition is not None:
                self.on_transition(res, lease, "committed")
            res.leases.clear()
            self.worker_rec(wid).committed += 1
            self._count(f"{self._noun}s_committed")
            self._settle_locked()
            return res, lease

    def fail(self, wid: str, index: int, epoch: int,
             causes: Optional[List[dict]] = None) -> dict:
        """A worker reports it could not serve its leased resource.
        Reassigns under cause ``poison_worker`` and drains the worker
        past the policy bound. Returns {"stale": bool, "drained": bool}.
        """
        with self.lock:
            lease = self.current_lease(index, epoch, wid)
            if lease is None:
                return {"stale": True, "drained": False}
            res = self.resources[index]
            res.leases.pop(epoch, None)
            res.failures.append({"worker": wid, "causes": causes or []})
            res.failed_workers.add(wid)
            worker = self.worker_rec(wid)
            worker.failed.add(index)
            if self.on_transition is not None:
                self.on_transition(res, lease, "failed")
            self._reassign_locked(res, lease, "poison_worker")
            if len(worker.failed) >= self.policy.poison_failures \
                    and not worker.drained:
                worker.drained = True
                self._count("workers_drained")
                self.revoke_worker(wid, "poison_worker")
            return {"stale": False, "drained": worker.drained}

    def bye(self, wid: str) -> None:
        with self.lock:
            self.worker_rec(wid).bye = True

    def control_closed(self, wid: str, clean: bool) -> None:
        """The worker's control connection ended. A dirty close (no
        ``bye``) with leases held is a crashed/killed worker — reassign
        everything it was running immediately."""
        with self.lock:
            worker = self.worker_rec(str(wid))
            if clean or worker.bye:
                return
            worker.dead = True
            self.revoke_worker(str(wid), "worker_exit")

    def revoke_worker(self, wid: str, cause: str) -> List[Resource]:
        """Revoke every lease ``wid`` holds; returns the resources that
        went back into play (the fleet resubmits their in-flight work)."""
        revoked: List[Resource] = []
        with self.lock:
            for res in self.resources:
                for epoch, lease in list(res.leases.items()):
                    if lease.worker == wid:
                        res.leases.pop(epoch, None)
                        res.next_epoch = max(res.next_epoch, epoch + 1)
                        if self.on_transition is not None:
                            self.on_transition(res, lease, "revoked")
                        self._reassign_locked(res, lease, cause)
                        revoked.append(res)
        return revoked

    def revoke_lease(self, index: int, epoch: int, cause: str) -> bool:
        """Revoke one specific lease (the fleet's scale-out rebalance);
        False when the (index, epoch) lease no longer exists."""
        with self.lock:
            if not (0 <= index < len(self.resources)):
                return False
            res = self.resources[index]
            lease = res.leases.pop(epoch, None)
            if lease is None:
                return False
            res.next_epoch = max(res.next_epoch, epoch + 1)
            if self.on_transition is not None:
                self.on_transition(res, lease, "revoked")
            self._reassign_locked(res, lease, cause)
            return True

    def _reassign_locked(self, res: Resource, lease: Lease,
                         cause: str) -> None:
        """Record one reassignment and put the resource back in play (or
        quarantine it once it has bounced past the policy bound)."""
        self.reassignments.append({
            self.key_field: res.key, "index": res.index,
            "worker": lease.worker, "epoch": lease.epoch, "cause": cause,
        })
        self._trim_locked(self.reassignments)
        res.reassigns += 1
        self._count("reassignments")
        if res.settled:
            return
        exhausted = (
            res.reassigns > self.policy.max_reassigns
            or len(res.failed_workers)
            >= self.policy.resource_fail_workers
        )
        if exhausted and not res.leases:
            res.status = "quarantined"
            self._count(f"{self._noun}s_quarantined")
            if self.on_transition is not None:
                self.on_transition(res, lease, "quarantined")
            self._settle_locked()
            return
        if not res.leases:
            res.status = "pending"
        if res.index not in self._pending and not exhausted:
            self._pending.appendleft(res.index)

    # -------------------------------------------------------- monitor passes
    def expire_pass(self) -> None:
        """Revoke every lease whose heartbeat went stale past the TTL
        (cause ``stale_heartbeat``)."""
        now = time.monotonic()
        with self.lock:
            for res in self.resources:
                for epoch, lease in list(res.leases.items()):
                    if now > lease.expires_at:
                        res.leases.pop(epoch, None)
                        if self.on_transition is not None:
                            self.on_transition(res, lease, "revoked")
                        self._reassign_locked(res, lease,
                                              "stale_heartbeat")

    def elect_straggler(self) -> Optional[Tuple[Resource, Lease]]:
        """Phase one of speculative re-execution: pick the one resource
        whose single lease has outlived the rolling-median bound. The
        caller fires its fault point outside the lock, then
        :meth:`confirm_steal` or :meth:`veto_steal`."""
        now = time.monotonic()
        with self.lock:
            if self.policy.straggler_factor <= 0:
                return None
            walls = sorted(
                r.wall_s for r in self.resources
                if r.status == "committed" and r.wall_s > 0
            )
            if len(walls) < max(self.policy.straggler_min_done, 1):
                return None
            n = len(walls)
            median = walls[n // 2] if n % 2 else 0.5 * (
                walls[n // 2 - 1] + walls[n // 2]
            )
            bound = max(self.policy.straggler_min_s,
                        self.policy.straggler_factor * median)
            for res in self.resources:
                if res.settled or res.straggled or len(res.leases) != 1:
                    continue
                (lease,) = res.leases.values()
                if now - lease.granted_at > bound:
                    res.straggled = True
                    return res, lease
        return None

    def confirm_steal(self, res: Resource, lease: Lease) -> None:
        with self.lock:
            if res.settled or not res.leases:
                return
            self.reassignments.append({
                self.key_field: res.key, "index": res.index,
                "worker": lease.worker, "epoch": lease.epoch,
                "cause": "straggler",
            })
            self._trim_locked(self.reassignments)
            res.reassigns += 1  # straggler dups count toward the bound
            self._count("reassignments")
            self._count("stragglers")
            if res.index not in self._pending:
                self._pending.appendleft(res.index)

    def veto_steal(self, res: Resource) -> None:
        with self.lock:
            res.straggled = False  # election vetoed; retry later

    # --------------------------------------------------------------- settle
    def _settle_locked(self) -> None:
        self._settled = sum(1 for r in self.resources if r.settled)
        if self._settled == len(self.resources):
            self.wall_s = time.monotonic() - self._t0
            self.done_event.set()

    @property
    def settled_count(self) -> int:
        with self.lock:
            return self._settled

    def take_cleanup_targets(self) -> List[Resource]:
        """Quarantined resources not yet swept (marks them swept): the
        client's sweep runs OUTSIDE the lock."""
        with self.lock:
            targets = [
                r for r in self.resources
                if r.status == "quarantined" and not r.cleaned
            ]
            for res in targets:
                res.cleaned = True
            return targets

    def pending_snapshot(self) -> List[int]:
        with self.lock:
            return list(self._pending)

    def run_wall_s(self) -> float:
        with self.lock:
            return self.wall_s or (time.monotonic() - self._t0)

    def holder(self, index: int) -> Optional[Tuple[str, int]]:
        """(worker, epoch) of the resource's single active lease, None
        while unheld (pending / being rebalanced). Resources under a
        straggler duplicate report the newest epoch."""
        with self.lock:
            if not (0 <= index < len(self.resources)):
                return None
            leases = self.resources[index].leases
            if not leases:
                return None
            epoch = max(leases)
            return leases[epoch].worker, epoch


# --------------------------------------------------------- wire protocol
#: the JSON-lines plain-socket protocol the lease clients share
#: (elastic map coordinator/workers, the serve fleet): one JSON document
#: per line, request/response on a persistent control connection, fresh
#: one-shot connections for heartbeats.
def send_line(sock: socket.socket, doc: dict) -> None:
    sock.sendall((json.dumps(doc) + "\n").encode())


def recv_line(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


def connect_timeout(default: float = 5.0) -> float:
    """The explicit connect timeout (``TMR_ELASTIC_CONNECT_TIMEOUT_S``,
    read lazily) every lease-protocol dial uses: a black-holed
    coordinator address must fail a worker FAST — the OS default connect
    timeout can park a worker in ``hello`` for minutes."""
    return max(_env_float("TMR_ELASTIC_CONNECT_TIMEOUT_S", default), 0.05)


def oneshot(address: Tuple[str, int], doc: dict,
            timeout: float = 10.0) -> dict:
    """One request/response on a fresh connection (heartbeats use this
    so beats never interleave with the control channel). The dial is
    bounded by :func:`connect_timeout`; ``timeout`` bounds the exchange
    after the connection is up."""
    with socket.create_connection(
        address, timeout=connect_timeout(min(timeout, 5.0))
    ) as sock:
        sock.settimeout(timeout)
        send_line(sock, doc)
        with sock.makefile("rb") as f:
            reply = recv_line(f)
    if reply is None:
        raise ConnectionError("coordinator closed the connection")
    return reply
