"""Sharding rules: parameter partition specs + batch specs.

Name-based rules in the spirit of pjit partitioning tables. The ViT's
attention and MLP feature dimensions shard over the 'model' axis (classic
Megatron-style TP: qkv/lin1 split the output features -> proj/lin2 split the
input features, so XLA inserts a single reduce-scatter/all-reduce pair per
block over ICI). Everything else replicates. The batch dimension of every
input shards over 'data' — that single annotation is the whole DDP
replacement: XLA derives the gradient psum from it.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
from flax import traverse_util
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_spec(path: Tuple[str, ...], leaf) -> P:
    """Partition spec for one parameter, by its tree path."""
    names = [str(p) for p in path]
    name = names[-1]
    joined = "/".join(names)
    ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))

    if "backbone" in names:
        # ViT TP: column-parallel qkv & mlp.lin1, row-parallel proj & mlp.lin2
        if "qkv" in names and name == "kernel":
            return P(None, "model")
        if "proj" in names and name == "kernel":
            return P("model", None)
        if "lin1" in names and name == "kernel":
            return P(None, "model")
        if "lin2" in names and name == "kernel":
            return P("model", None)
        if "qkv" in names and name == "bias":
            return P("model")
        if "lin1" in names and name == "bias":
            return P("model")
        if name == "kernel" and "patch_embed" in joined and ndim == 4:
            return P(None, None, None, "model")  # embed dim
        if name == "pos_embed":
            return P(None, None, None, "model")
    # heads/decoders: small, replicate
    return P()


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Apply NamedSharding to a param tree (device_put with per-leaf specs)."""
    flat = traverse_util.flatten_dict(params)
    placed = {
        path: jax.device_put(leaf, NamedSharding(mesh, param_spec(path, leaf)))
        for path, leaf in flat.items()
    }
    return traverse_util.unflatten_dict(placed)


def params_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding tree matching ``params`` (for jit in_shardings)."""
    flat = traverse_util.flatten_dict(params)
    out = {
        path: NamedSharding(mesh, param_spec(path, leaf))
        for path, leaf in flat.items()
    }
    return traverse_util.unflatten_dict(out)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs shard their leading (batch) dim over 'data'."""
    return NamedSharding(mesh, P("data"))


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    bs = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, bs), batch)


def state_sharding(state, mesh: Mesh):
    """Sharding tree for a TrainState.

    Params get exact per-path specs. AdamW moments (mu/nu) mirror parameter
    shapes, so optimizer-state leaves inherit the spec of the first parameter
    with the same shape (sharded params have distinctive shapes; anything
    unmatched — step counters, scalars — replicates).
    """
    flat_params = traverse_util.flatten_dict(state.params)
    by_shape = {}
    for path, leaf in flat_params.items():
        by_shape.setdefault(leaf.shape, NamedSharding(mesh, param_spec(path, leaf)))

    def assign(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) > 0 and shape in by_shape:
            return by_shape[shape]
        return NamedSharding(mesh, P())

    tree = jax.tree_util.tree_map(assign, state)
    return tree.replace(params=params_shardings(state.params, mesh))
