"""Sharding rules: parameter partition specs + batch specs.

Name-based rules in the spirit of pjit partitioning tables. The ViT's
attention and MLP feature dimensions shard over the 'model' axis (classic
Megatron-style TP: qkv/lin1 split the output features -> proj/lin2 split the
input features, so XLA inserts a single reduce-scatter/all-reduce pair per
block over ICI). Everything else replicates. The batch dimension of every
input shards over 'data' — that single annotation is the whole DDP
replacement: XLA derives the gradient psum from it.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
from flax import traverse_util
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_spec(path: Tuple[str, ...], leaf) -> P:
    """Partition spec for one parameter, by its tree path."""
    names = [str(p) for p in path]
    name = names[-1]
    joined = "/".join(names)
    ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))

    if "backbone" in names:
        # ViT TP: column-parallel qkv & mlp.lin1, row-parallel proj & mlp.lin2
        if "qkv" in names and name == "kernel":
            return P(None, "model")
        if "proj" in names and name == "kernel":
            return P("model", None)
        if "lin1" in names and name == "kernel":
            return P(None, "model")
        if "lin2" in names and name == "kernel":
            return P("model", None)
        if "qkv" in names and name == "bias":
            return P("model")
        if "lin1" in names and name == "bias":
            return P("model")
        if name == "kernel" and "patch_embed" in joined and ndim == 4:
            return P(None, None, None, "model")  # embed dim
        if name == "pos_embed":
            return P(None, None, None, "model")
    # heads/decoders: small, replicate
    return P()


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Apply NamedSharding to a param tree (device_put with per-leaf specs)."""
    flat = traverse_util.flatten_dict(params)
    placed = {
        path: jax.device_put(leaf, NamedSharding(mesh, param_spec(path, leaf)))
        for path, leaf in flat.items()
    }
    return traverse_util.unflatten_dict(placed)


def params_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding tree matching ``params`` (for jit in_shardings)."""
    flat = traverse_util.flatten_dict(params)
    out = {
        path: NamedSharding(mesh, param_spec(path, leaf))
        for path, leaf in flat.items()
    }
    return traverse_util.unflatten_dict(out)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs shard their leading (batch) dim over 'data'."""
    return NamedSharding(mesh, P("data"))


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    bs = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, bs), batch)


def state_sharding(state, mesh: Mesh):
    """Sharding tree for a TrainState.

    Optimizer moments (AdamW mu/nu) are pytrees with the *same dict nesting*
    as the params they track, so every leaf is matched by the dict-key path
    it shares with its parameter. Wrappers may prefix that path with extra
    dict keys — ``optax.multi_transform`` (the production two-LR-group
    optimizer, train/state.py) nests each moment tree under its group label,
    e.g. ``inner_states['backbone'].mu['backbone']['blocks_0'][...]`` — so
    the *longest suffix* of the leaf's dict path that names a parameter
    wins. No shape heuristics: two same-shaped params with different specs
    cannot collide (the round-2 verdict flagged exactly that risk in the
    previous by-shape implementation). Leaves matching no param path (step
    counters, masked-out optax nodes, scalars) replicate.
    """
    flat_specs = {
        path: NamedSharding(mesh, param_spec(path, leaf))
        for path, leaf in traverse_util.flatten_dict(state.params).items()
    }
    replicated = NamedSharding(mesh, P())

    def assign(path, leaf):
        names = tuple(
            k.key for k in path if isinstance(k, jax.tree_util.DictKey)
        )
        for i in range(len(names)):  # longest suffix first
            spec = flat_specs.get(names[i:])
            if spec is not None:
                return spec
        return replicated

    return jax.tree_util.tree_map_with_path(assign, state)


def serve_param_spec(path: Tuple[str, ...], leaf,
                     tp_axis: str = "tp") -> P:
    """:func:`param_spec` with the training-mesh ``'model'`` axis
    renamed onto the serving mesh's tensor-parallel axis — one rule
    table for both sides (a drifted copy was the alternative)."""
    spec = param_spec(path, leaf)
    return P(*(tp_axis if ax == "model" else ax for ax in spec))


def serve_param_shardings(params: Any, mesh: Mesh,
                          tp_axis: str = "tp") -> Any:
    """NamedSharding tree for the serving mesh: ViT feature dims over
    ``tp_axis`` (when the mesh has that axis with size > 1), everything
    else replicated — the ``in_shardings`` for the tensor-parallel serve
    programs and the ``device_put`` specs the stager commits params
    with."""
    has_tp = dict(mesh.shape).get(tp_axis, 1) > 1
    flat = traverse_util.flatten_dict(params)
    out = {
        path: NamedSharding(
            mesh,
            serve_param_spec(path, leaf, tp_axis) if has_tp else P(),
        )
        for path, leaf in flat.items()
    }
    return traverse_util.unflatten_dict(out)


def validate_tp(mesh: Mesh, embed_dim: int, num_heads: int,
                mlp_ratio: float = 4.0, axis: str = "model") -> None:
    """Fail fast when the ViT widths don't divide the tensor-parallel
    axis (``'model'`` on the training mesh, ``'tp'`` on the serving
    mesh — pass ``axis``).

    Megatron-style TP shards qkv/lin1 output features and proj/lin2 input
    features; uneven splits would silently produce ragged shards (or XLA
    padding) — refuse instead.
    """
    tp = mesh.shape.get(axis, 1)
    if tp <= 1:
        return
    problems = []
    if embed_dim % tp:
        problems.append(f"embed_dim {embed_dim} % model axis {tp} != 0")
    if num_heads % tp:
        problems.append(f"num_heads {num_heads} % model axis {tp} != 0")
    if int(embed_dim * mlp_ratio) % tp:
        problems.append(
            f"mlp dim {int(embed_dim * mlp_ratio)} % model axis {tp} != 0"
        )
    if problems:
        raise ValueError("tensor parallelism misfit: " + "; ".join(problems))
