"""Pipeline parallelism (GPipe) for the SAM ViT encoder.

The reference scales only by data parallelism (Lightning DDP); this module
adds the remaining classic axis: partition the encoder's transformer blocks
into pipeline stages sharded over a 'pipe' mesh axis, stream microbatches
through the stages, and rotate activations stage-to-stage with
``lax.ppermute`` over ICI neighbor links.

The SAM ViTs are unusually pipeline-friendly: their global-attention
indexes (sam_ViT.py / vit.py VIT_CONFIGS — vit_b (2,5,8,11) of depth 12,
vit_h (7,15,23,31) of depth 32) sit at the END of equal-size block groups,
so every stage has the identical structure "d-1 windowed blocks + 1 global
block". Identical structure means identical parameter PyTrees, so all
stages stack into one tree with a leading stage axis, that axis shards over
'pipe', and ONE traced stage computation serves every device — the
homogeneity SPMD pipelining needs (no per-stage branches).

Schedule: plain GPipe under ``lax.scan`` (differentiable — the backward
pipeline is XLA-derived, bubbles and all): M microbatches over P stages run
M + P - 1 ticks; stage 0 injects microbatch t, stage P-1 records microbatch
t-(P-1), everyone ppermutes its activation forward each tick. Outputs are
zero everywhere except the last stage and are combined with one closing
``psum`` (replicated result — the simple, correct v1; a reduce-scatter
variant can shard it later).

Scope note: this pipelines the ENCODER FORWARD/BACKWARD (the FLOPs/memory
dominant part — the detector head is a few convs). It composes under jit
with data parallelism on the batch dim outside the island. Trainer wiring
(``--mesh_pipe``): ``create_pp_train_state``/``make_pp_train_step`` hold
params and AdamW moments in the stage-major layout sharded over 'pipe'
(``pp_state_sharding``), the detector head runs densely on the island's
output, and eval/checkpoint interop converts layouts via
``unstack_backbone_params``. TP/SP inside a pipe mesh is not composed.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def stage_split(depth: int, global_attn_indexes: Sequence[int]) -> Tuple[int, int]:
    """(n_stages, blocks_per_stage) — validates the homogeneity invariant:
    every stage must be 'd-1 windowed + 1 global' so stage params stack."""
    n = len(global_attn_indexes)
    if n == 0 or depth % n:
        raise ValueError(
            f"depth {depth} not divisible into {n} stages (one per global "
            "block)"
        )
    d = depth // n
    expected = tuple((s + 1) * d - 1 for s in range(n))
    got = tuple(sorted(int(i) for i in global_attn_indexes))
    if got != expected:
        raise ValueError(
            f"global_attn_indexes {got} do not close equal-size stages "
            f"{expected}; heterogeneous stages cannot be pipelined"
        )
    return n, d


def stack_stage_params(params: dict, depth: int,
                       global_attn_indexes: Sequence[int]) -> dict:
    """SamViT 'blocks_i' params -> one stage-major tree with a leading
    stage axis: out['b{j}'] has shape (P, ...) stacking block s*d+j over
    stages s. Inverse layout of vit.py's flat naming; shapes agree across
    stages by the stage_split invariant."""
    n, d = stage_split(depth, global_attn_indexes)
    stages = [
        {f"b{j}": params[f"blocks_{s * d + j}"] for j in range(d)}
        for s in range(n)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def _stage_blocks(vit):
    """One stage's Block modules: d-1 windowed + 1 global (static configs,
    same for every stage). rel_pos_size is the PRETRAIN grid — parameter
    shapes are fixed there and get_rel_pos interpolates to the runtime grid,
    exactly as SamViT.__call__ builds its blocks."""
    from tmr_tpu.models.vit import Block

    if getattr(vit, "seq_mesh", None) is not None:
        # the rebuilt Blocks below don't forward seq_mesh/batch_axis, so a
        # ring/sequence-parallel SamViT would silently run dense attention
        # inside the pipeline island — refuse instead of dropping the config
        raise ValueError(
            "pipeline parallelism does not compose with vit.seq_mesh "
            "(sequence-parallel attention); build the SamViT without "
            "seq_mesh to pipeline it"
        )
    _, d = stage_split(vit.depth, vit.global_attn_indexes)
    grid = vit.pretrain_img_size // vit.patch_size
    # honour --remat_backbone inside the island too (same silent-drop class
    # as the seq_mesh refusal above): the pipeline is chosen exactly for
    # big-model training, where dropping remat means depth x activation mem
    from flax import linen as nn

    block_cls = nn.remat(Block) if getattr(vit, "remat", False) else Block
    blocks = []
    for j in range(d):
        blocks.append(
            block_cls(
                num_heads=vit.num_heads,
                mlp_ratio=vit.mlp_ratio,
                window_size=0 if j == d - 1 else vit.window_size,
                rel_pos_size=(grid, grid),
                dtype=vit.dtype,
            )
        )
    return blocks


def pipeline_blocks_apply(
    vit,
    stacked: dict,
    x: jnp.ndarray,
    mesh,
    axis: str = "pipe",
    microbatches: int = 2,
    data_axis: str = None,
) -> jnp.ndarray:
    """Run the ViT's transformer blocks as a GPipe pipeline over ``axis``.

    vit: the SamViT module (for static block configs); stacked: the
    stage-major params of stack_stage_params, leading axis sharded over
    ``axis``; x: (B, h, w, C) tokens AFTER patch/pos embed. Returns the
    (B, h, w, C) tokens the dense block stack would produce (same floats up
    to fp reordering).

    ``data_axis`` composes pp x dp in one mesh: each microbatch's batch dim
    additionally shards over that axis (every (pipe, data) device pair
    pipelines its own batch shard; the closing psum runs over 'pipe' only,
    so the output keeps the data sharding).
    """
    n_stage, _ = stage_split(vit.depth, vit.global_attn_indexes)
    if mesh.shape[axis] != n_stage:
        # a mismatch would silently drop stages: shard_map splits the stage
        # axis across devices and each device keeps only its slice's [0]
        raise ValueError(
            f"'{axis}' mesh axis is {mesh.shape[axis]} devices but the "
            f"model splits into {n_stage} stages; they must match"
        )
    b = x.shape[0]
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible into {microbatches} "
                         "microbatches")
    blocks = _stage_blocks(vit)

    def stage_fn(stage_params, h):
        for j, blk in enumerate(blocks):
            h = blk.apply({"params": stage_params[f"b{j}"]}, h)
        return h

    mb = b // microbatches
    if data_axis is not None and mb % mesh.shape[data_axis]:
        raise ValueError(
            f"microbatch size {mb} not divisible by '{data_axis}' axis "
            f"size {mesh.shape[data_axis]}"
        )
    x_mb = x.reshape((microbatches, mb) + x.shape[1:])

    def island(stacked_local, x_all):
        sid = lax.axis_index(axis)
        params = jax.tree.map(lambda a: a[0], stacked_local)
        buf = jnp.zeros_like(x_all[0])
        out = jnp.zeros_like(x_all)

        def tick(carry, t):
            buf, out = carry
            inject = x_all[jnp.clip(t, 0, microbatches - 1)]
            h_in = jnp.where(sid == 0, inject, buf)
            y = stage_fn(params, h_in)
            oidx = t - (n_stage - 1)
            record = (sid == n_stage - 1) & (oidx >= 0)
            out = out.at[jnp.clip(oidx, 0, microbatches - 1)].add(
                jnp.where(record, y, jnp.zeros_like(y))
            )
            perm = [(j, (j + 1) % n_stage) for j in range(n_stage)]
            buf = lax.ppermute(y, axis, perm)
            return (buf, out), None

        (buf, out), _ = lax.scan(
            tick, (buf, out), jnp.arange(microbatches + n_stage - 1)
        )
        # outputs were recorded on the last stage only; combine + replicate
        return lax.psum(out, axis)

    x_spec = P(None, data_axis) if data_axis is not None else P()
    from tmr_tpu.parallel.compat import shard_map

    island_sharded = shard_map(
        island,
        mesh=mesh,
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    out = island_sharded(stacked, x_mb)
    return out.reshape((b,) + x.shape[1:])


def pipeline_vit_apply(
    vit,
    params: dict,
    image: jnp.ndarray,
    mesh,
    axis: str = "pipe",
    microbatches: int = 2,
    data_axis: str = None,
) -> jnp.ndarray:
    """Full pipelined encoder forward: replicated patch/pos embed, the
    block pipeline island, replicated neck. Numerically equivalent to
    ``vit.apply`` (tests/test_pipeline.py pins it, forward and grads).

    The pre/post stages run through SamViT's OWN ``embed``/``neck`` methods
    (``apply(method=...)``) — one definition for the dense and pipelined
    forward, so they cannot drift. The blocks come flat ('blocks_0' present,
    stacked here) or pre-stacked under 'stages' (the stage-sharded
    deployment layout, see stage_sharding).
    """
    if "blocks_0" in params:
        stacked = stack_stage_params(
            params, vit.depth, vit.global_attn_indexes
        )
    else:
        stacked = params["stages"]

    x = vit.apply({"params": params}, image, method="embed")
    x = pipeline_blocks_apply(
        vit, stacked, x, mesh, axis=axis, microbatches=microbatches,
        data_axis=data_axis,
    )
    return vit.apply({"params": params}, x, method="neck")


# --------------------------------------------------------- Trainer wiring
def stack_backbone_params(params: dict, vit) -> dict:
    """MatchingNet param tree -> pipeline layout: the backbone's flat
    'blocks_i' subtrees become one stage-major 'stages' tree (leading stage
    axis, shardable over 'pipe'); embed/neck/head params are untouched."""
    bb = dict(params["backbone"])
    stacked = stack_stage_params(bb, vit.depth, vit.global_attn_indexes)
    out = {k: v for k, v in bb.items() if not k.startswith("blocks_")}
    out["stages"] = stacked
    return {**params, "backbone": out}


def unstack_backbone_params(params: dict, vit) -> dict:
    """Inverse of stack_backbone_params: pipeline layout -> the dense flat
    'blocks_i' layout every non-pipelined consumer (Predictor, converter,
    export) expects. Used when a pp-trained state feeds eval/checkpoint
    interop."""
    if "stages" not in params.get("backbone", {}):
        return params
    n, d = stage_split(vit.depth, vit.global_attn_indexes)
    bb = {k: v for k, v in params["backbone"].items() if k != "stages"}
    stages = params["backbone"]["stages"]
    for s in range(n):
        for j in range(d):
            bb[f"blocks_{s * d + j}"] = jax.tree.map(
                lambda a, _s=s: a[_s], stages[f"b{j}"]
            )
    return {**params, "backbone": bb}


def pp_state_sharding(state, mesh, axis: str = "pipe"):
    """Sharding tree for a pipeline-layout TrainState: every leaf under a
    'stages' subtree shards its leading (stage) axis over ``axis`` — params
    AND their AdamW moments, which mirror the param dict nesting — and
    everything else replicates. Megatron-style TP inside a pp mesh is not
    composed here (v1): the pp mesh carries ('data', 'pipe') only."""
    from jax.sharding import NamedSharding, PartitionSpec

    def assign(path, leaf):
        names = tuple(
            k.key for k in path if isinstance(k, jax.tree_util.DictKey)
        )
        if "stages" in names and getattr(leaf, "ndim", 0) >= 1:
            return NamedSharding(
                mesh, PartitionSpec(axis, *([None] * (leaf.ndim - 1)))
            )
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree_util.tree_map_with_path(assign, state)


def create_pp_train_state(
    model, cfg, rng, sample_image, sample_exemplars, steps_per_epoch: int = 1000
):
    """create_train_state in the pipeline layout: init the dense model, stack
    the backbone blocks stage-major, then build the optimizer ON the stacked
    tree — AdamW moments come out stage-major too, so one sharding rule
    (pp_state_sharding) places params and optimizer state consistently."""
    from tmr_tpu.train.state import TrainState, make_optimizer

    params = jax.jit(model.init)(rng, sample_image, sample_exemplars)["params"]
    params = stack_backbone_params(params, model.backbone)
    tx = make_optimizer(cfg, steps_per_epoch)
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)


def make_pp_train_step(
    model, cfg, mesh, microbatches: int = 0, data_axis: str = None
):
    """Pipeline-parallel train step: the encoder forward/backward runs as the
    GPipe island over 'pipe' (optionally x data parallel over 'data'), the
    detector head + loss + optimizer share make_train_step's logic via its
    forward_fn hook. Expects a state from create_pp_train_state.

    microbatches 0 -> auto: the most microbatches <= the stage count that
    still divide the batch (and keep each microbatch divisible by the 'data'
    axis) — the standard GPipe bubble/memory point, degrading gracefully for
    small batches instead of failing the divisibility checks.
    """
    from tmr_tpu.train.state import make_train_step

    n_stage, _ = stage_split(
        model.backbone.depth, model.backbone.global_attn_indexes
    )
    nd = mesh.shape.get(data_axis, 1) if data_axis is not None else 1

    def pick_microbatches(b: int) -> int:
        if microbatches > 0:
            return microbatches
        for m in range(min(n_stage, b), 0, -1):
            if b % m == 0 and (b // m) % nd == 0:
                return m
        return 1

    def forward(params, image, exemplars):
        feat = pipeline_vit_apply(
            model.backbone, params["backbone"], image, mesh,
            microbatches=pick_microbatches(int(image.shape[0])),
            data_axis=data_axis,
        )
        return model.apply(
            {"params": params}, image, exemplars, features=feat
        )

    return make_train_step(model, cfg, forward_fn=forward)


def stage_sharding(stacked: dict, mesh, axis: str = "pipe"):
    """NamedShardings placing each stage's params on its pipe device (the
    leading stage axis sharded over ``axis``, everything else replicated)."""
    def spec(leaf):
        return NamedSharding(
            mesh, P(axis, *([None] * (leaf.ndim - 1)))
        )

    return jax.tree.map(spec, stacked)
