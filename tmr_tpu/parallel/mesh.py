"""Mesh construction + multi-host init helpers."""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    shape: Tuple[int, ...] = (-1, 1),
    axis_names: Optional[Tuple[str, ...]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a device mesh — ('data', 'model') by default, or
    ('data', 'model', 'seq') when a third (sequence/context-parallel) size
    is given. A single -1 entry fills with all remaining devices. Works
    identically on a real slice and on the virtual CPU mesh used in
    tests/dry runs.

    Device order: jax.experimental.mesh_utils picks an ICI-friendly layout on
    real TPU topologies; on hosts it's the flat device list.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_names is None:
        axis_names = ("data", "model", "seq")[: len(shape)]
    elif len(shape) != len(axis_names):
        raise ValueError(
            f"shape {shape} and axis_names {axis_names} length mismatch"
        )
    sizes = list(shape)
    if sizes.count(-1) > 1:
        raise ValueError("at most one -1 mesh dimension")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % fixed:
            raise ValueError(f"{len(devices)} devices not divisible by {fixed}")
        sizes[sizes.index(-1)] = len(devices) // fixed
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh {sizes} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(tuple(sizes), devices=devices[:n])
    except Exception:
        arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, tuple(axis_names))


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host (DCN) initialization — the reference's multi-node story is
    Hadoop job submission; ours is jax.distributed over the pod.

    No-op when single-process (the common case in this image)."""
    if num_processes in (None, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


# ---------------------------------------------------------------- serving

#: axis names of the serving mesh: batches shard over ``dp`` (replica
#: groups), the ViT feature dimensions shard over ``tp`` inside a group
SERVE_AXES = ("dp", "tp")

_SPEC_RE = re.compile(r"(dp|tp)(\d+)")


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse a serving-mesh spec string into ``{"dp": N, "tp": M}``.

    The spec is a concatenation of ``dp<N>`` / ``tp<M>`` terms in any
    order (``"dp4"``, ``"tp4"``, ``"dp2tp2"``); an omitted axis is 1.
    Raises ValueError on anything else — a typo'd ``TMR_SERVE_MESH``
    must fail engine construction loudly, not silently serve unsharded.
    """
    s = (spec or "").strip().lower()
    if not s:
        raise ValueError("empty mesh spec")
    out = {"dp": 1, "tp": 1}
    seen = set()
    pos = 0
    for m in _SPEC_RE.finditer(s):
        if m.start() != pos:
            break
        axis, n = m.group(1), int(m.group(2))
        if axis in seen:
            raise ValueError(f"mesh spec {spec!r}: duplicate {axis!r}")
        if n < 1:
            raise ValueError(f"mesh spec {spec!r}: {axis}{n} < 1")
        seen.add(axis)
        out[axis] = n
        pos = m.end()
    if pos != len(s) or not seen:
        raise ValueError(
            f"bad mesh spec {spec!r}: expected dp<N>/tp<M> terms, "
            "e.g. 'dp4', 'tp4', 'dp2tp2'"
        )
    return out


def make_serve_mesh(spec: str,
                    devices: Optional[Sequence] = None) -> Mesh:
    """Build the serving mesh for ``spec`` over the leading
    ``dp * tp`` local devices: axes ``("dp", "tp")``, row-major — the
    ``tp`` rows are the replica groups (see :func:`replica_groups`).
    Unlike :func:`make_mesh` the device order is the flat local list on
    every backend: serving replica groups must be stable across engine
    restarts for the compiled-program cache keys to hit."""
    sizes = parse_mesh_spec(spec)
    devices = list(devices if devices is not None else jax.devices())
    need = sizes["dp"] * sizes["tp"]
    if need > len(devices):
        raise ValueError(
            f"mesh {spec!r} needs {need} devices, have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(sizes["dp"], sizes["tp"])
    return Mesh(arr, SERVE_AXES)


def replica_groups(mesh: Mesh) -> List[List]:
    """The serving mesh's replica groups: one list of devices per ``dp``
    index (each group spans the ``tp`` axis — the devices one
    tensor-parallel program executes across)."""
    arr = np.asarray(mesh.devices)
    if arr.ndim != 2:
        raise ValueError(f"expected a 2-axis serve mesh, got {arr.shape}")
    return [list(row) for row in arr]
