"""Mesh construction + multi-host init helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    shape: Tuple[int, ...] = (-1, 1),
    axis_names: Optional[Tuple[str, ...]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a device mesh — ('data', 'model') by default, or
    ('data', 'model', 'seq') when a third (sequence/context-parallel) size
    is given. A single -1 entry fills with all remaining devices. Works
    identically on a real slice and on the virtual CPU mesh used in
    tests/dry runs.

    Device order: jax.experimental.mesh_utils picks an ICI-friendly layout on
    real TPU topologies; on hosts it's the flat device list.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_names is None:
        axis_names = ("data", "model", "seq")[: len(shape)]
    elif len(shape) != len(axis_names):
        raise ValueError(
            f"shape {shape} and axis_names {axis_names} length mismatch"
        )
    sizes = list(shape)
    if sizes.count(-1) > 1:
        raise ValueError("at most one -1 mesh dimension")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % fixed:
            raise ValueError(f"{len(devices)} devices not divisible by {fixed}")
        sizes[sizes.index(-1)] = len(devices) // fixed
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh {sizes} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(tuple(sizes), devices=devices[:n])
    except Exception:
        arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, tuple(axis_names))


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host (DCN) initialization — the reference's multi-node story is
    Hadoop job submission; ours is jax.distributed over the pod.

    No-op when single-process (the common case in this image)."""
    if num_processes in (None, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
