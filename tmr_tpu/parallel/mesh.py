"""Mesh construction + multi-host init helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    shape: Tuple[int, int] = (-1, 1),
    axis_names: Tuple[str, str] = ("data", "model"),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ('data', 'model') mesh. shape=(-1, tp) fills 'data' with all
    remaining devices. Works identically on a real slice and on the
    virtual CPU mesh used in tests/dry runs.

    Device order: jax.experimental.mesh_utils picks an ICI-friendly layout on
    real TPU topologies; on hosts it's the flat device list.
    """
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = shape
    if dp == -1:
        if len(devices) % tp:
            raise ValueError(f"{len(devices)} devices not divisible by tp={tp}")
        dp = len(devices) // tp
    n = dp * tp
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{tp} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh((dp, tp), devices=devices[:n])
    except Exception:
        arr = np.array(devices[:n]).reshape(dp, tp)
    return Mesh(arr, axis_names)


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host (DCN) initialization — the reference's multi-node story is
    Hadoop job submission; ours is jax.distributed over the pod.

    No-op when single-process (the common case in this image)."""
    if num_processes in (None, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
