"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context machinery (SURVEY §5.7): its SAM ViT bounds
attention cost with 14x14 windows and only 4 global-attention blocks over a
4096-token grid (sam_ViT.py:166-177), and escalates resolution to 1536 (9216
tokens) for small objects. This module makes sequence scaling first-class for
the TPU framework so the encoder (or any transformer) can grow past what one
chip's HBM holds:

- :func:`ring_attention` — blockwise attention with online-softmax
  accumulation; K/V shards rotate around the mesh axis ring via
  ``lax.ppermute`` so each device only ever materializes its local
  (S/n x S/n) score block. O(S) memory per device, exact (not approximate)
  attention, fp32 accumulation. Optional additive bias supplied per
  (q-shard, k-shard) pair via ``bias_fn`` — this is how the ViT's decomposed
  relative-position bias (sam_ViT.py:325-361) stays computable under
  sharding without materializing the full S x S bias.
- :func:`ulysses_attention` — the all-to-all alternative: resharding
  sequence -> heads with ``lax.all_to_all``, dense local attention over the
  full sequence for the local head group, then heads -> sequence back.
  Cheaper collectives on all-to-all-friendly fabrics when H >= n.

Both are pure jax functions meant to run inside ``shard_map`` over a mesh
axis (tests use the 8-device CPU mesh; on hardware the ring rides ICI
neighbor links). Both are differentiable (plain jax ops, so XLA derives the
backward ring).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn_update(q, k, v, bias, scale, m, l, o):
    """One online-softmax accumulation step over a K/V block.

    q: (B, H, Sq, D); k/v: (B, H, Sk, D); bias: (B|1, H|1, Sq, Sk) or None;
    m/l/o: running max (B, H, Sq), denom (B, H, Sq), accum (B, H, Sq, D).
    Returns updated (m, l, o). All f32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # rescale previous accumulators to the new max
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    bias_fn: Optional[Callable[[jnp.ndarray, jnp.ndarray], Optional[jnp.ndarray]]] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded along ``axis_name``.

    Each device holds q/k/v of shape (B, H, S_local, D) — its contiguous
    sequence shard. K/V rotate n-1 times around the ring; each step the
    device accumulates its q-block against the visiting k/v-block with the
    numerically stable online softmax. Output is the local (B, H, S_local, D)
    attention result, bitwise-equivalent (up to fp reordering) to dense
    softmax attention over the gathered sequence.

    ``bias_fn(q_index, k_index) -> (B|1, H|1, S_local, S_local) or None``
    receives the *shard indices* (traced int32) of the query block (fixed,
    this device) and the currently visiting key block, and returns the
    additive attention bias for that block pair — e.g. decomposed rel-pos
    sliced to the two shards' coordinate ranges.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    qf = q.astype(jnp.float32)
    B, H, S, D = q.shape
    m = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, H, S, D), jnp.float32)

    def accumulate(i, k_blk, v_blk, m, l, o):
        k_idx = (my - i) % n  # block that arrived after i rotations
        bias = bias_fn(my, k_idx) if bias_fn is not None else None
        return _block_attn_update(
            qf, k_blk.astype(jnp.float32), v_blk, bias, scale, m, l, o
        )

    def step(i, carry):
        k_blk, v_blk, m, l, o = carry
        m, l, o = accumulate(i, k_blk, v_blk, m, l, o)
        # pass k/v to the next device in the ring (receive from the previous)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    # n-1 rotations; the final visiting block is consumed without another
    # (dead) ppermute pair burning ICI bandwidth
    k_blk, v_blk, m, l, o = lax.fori_loop(0, n - 1, step, (k, v, m, l, o))
    m, l, o = accumulate(n - 1, k_blk, v_blk, m, l, o)
    out = o / l[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    In: (B, H, S_local, D) sequence-sharded. ``lax.all_to_all`` reshards to
    (B, H_local, S_full, D) — every device sees the full sequence for H/n
    heads — then dense softmax attention runs locally, and a second
    all-to-all reshards back to sequence. Requires H % n == 0.
    """
    n = lax.psum(1, axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    def seq_to_heads(x):
        # (B, H, S_local, D) -> concat over seq of (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return heads_to_seq(out.astype(q.dtype))


def dense_attention(q, k, v, bias=None, scale=None):
    """Single-device reference: softmax(q k^T * scale + bias) v, f32 accum.
    The oracle the ring/ulysses tests compare against."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def ring_decomposed_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    rel_h_table: jnp.ndarray,
    rel_w_table: jnp.ndarray,
    grid_w: int,
    axis_name: str,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring attention with the SAM ViT's decomposed relative-position bias
    (sam_ViT.py:325-361) for a token grid row-sharded over ``axis_name``.

    The (H_grid, W_grid) token grid is split into contiguous row bands; each
    device holds q/k/v (B, heads, rows_local * W_grid, head_dim) for its
    band. ``rel_h_table`` (H, H, hd) and ``rel_w_table`` (W, W, hd) are the
    full get_rel_pos outputs (replicated — ~1 MB at ViT scale, vs the
    S x S bias this avoids materializing). The bias for a (q-band, k-band)
    pair is rebuilt on the fly from the q band's features and a dynamic
    row-slice of the H-table, so the result matches the dense decomposed
    attention exactly (up to fp reordering).
    """
    B, H, S_local, D = q.shape
    rows_local = S_local // grid_w
    qf = q.astype(jnp.float32)
    r_q = qf.reshape(B, H, rows_local, grid_w, D)
    # rel_w term is k-band independent: (B, H, rows, W, W_k)
    rel_w = jnp.einsum(
        "bnhwc,wkc->bnhwk", r_q, rel_w_table.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )

    def bias_fn(q_idx, k_idx):
        rh = lax.dynamic_slice(
            rel_h_table.astype(jnp.float32),
            (q_idx * rows_local, k_idx * rows_local, 0),
            (rows_local, rows_local, rel_h_table.shape[-1]),
        )
        rel_h = jnp.einsum(
            "bnhwc,hkc->bnhwk", r_q, rh, preferred_element_type=jnp.float32
        )
        bias = rel_h[..., :, None] + rel_w[..., None, :]
        return bias.reshape(B, H, S_local, rows_local * grid_w)

    return ring_attention(q, k, v, axis_name, bias_fn=bias_fn, scale=scale)


def make_ring_attention_fn(
    mesh,
    axis_name: str = "seq",
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    decomposed: bool = False,
    grid_w: Optional[int] = None,
    scale: Optional[float] = None,
):
    """shard_map-wrapped ring attention over ``mesh``'s ``axis_name``:
    (B, H, S, D) global arrays in/out, sequence dim sharded internally.
    ``batch_axis``/``head_axis`` additionally shard batch (data parallel)
    and heads (tensor parallel) so the island composes with dp/tp meshes.
    With ``decomposed=True`` the callable takes (q, k, v, rel_h_table,
    rel_w_table) and applies the ViT decomposed rel-pos bias (``grid_w``
    required)."""
    from jax.sharding import PartitionSpec as P

    from tmr_tpu.parallel.compat import shard_map

    spec = P(batch_axis, head_axis, axis_name, None)
    if decomposed:
        if grid_w is None:
            raise ValueError("decomposed=True requires grid_w")
        return shard_map(
            partial(
                ring_decomposed_attention, grid_w=grid_w,
                axis_name=axis_name, scale=scale,
            ),
            mesh=mesh, in_specs=(spec, spec, spec, P(), P()),
            out_specs=spec, check_vma=False,
        )
    return shard_map(
        partial(ring_attention, axis_name=axis_name, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
