"""Elastic map phase: lease-based coordinator/worker shard execution.

PR 2's executor made the map phase crash-proof on ONE host walking a
static shard list; the reference repo's Hadoop Streaming layer got more
for free from the JobTracker — dead mappers reassigned, stragglers
speculatively re-executed. This module is that story rebuilt TPU-native,
layered on the durable journal so nothing about the single-process
correctness contract changes:

- the **coordinator** owns the shard queue as *leases*: an
  atomically-written ``<journal>/_leases/<stem>.json`` record
  (``atomicio.atomic_write``) carrying worker id, a monotonically
  increasing per-shard **epoch**, and an expiry. It serves a tiny
  JSON-lines TCP protocol (plain sockets — runs under
  ``JAX_PLATFORMS=cpu`` in tier-1 and multi-host JAX in production);
- **workers** (separate processes or threads) lease one shard at a
  time, run the existing ``mapreduce._run_stream_impl`` shard-attempt
  machinery unchanged (retry/backoff/stall-timeout/quarantine all
  apply), heartbeat the lease on an interval
  (``obs.flight.Heartbeat`` — the emit callable sends the beat), and
  commit the journal done-marker before releasing;
- **liveness** is PR 2's stall-timeout generalized across processes: a
  lease whose heartbeat goes stale past the TTL is revoked and the
  shard reassigned under an incremented epoch (cause
  ``stale_heartbeat``); a worker whose control connection drops while
  it holds a lease is reassigned immediately (``worker_exit``);
- **fencing** is what makes all of that safe: every journal commit is
  fenced on the CURRENT lease epoch (``journal.record(fence=...)`` →
  a precommit round-trip). A paused-then-resumed worker whose lease was
  revoked raises :class:`StaleLeaseError` before its marker touches
  disk — it can never corrupt the table — and the rejection is counted
  in the report. The journal's digest check plus ``atomic_save_npy``
  idempotence already make double-execution of the FEATURE writes
  harmless;
- **stragglers**: when a shard's runtime exceeds a rolling-median-based
  bound, the coordinator duplicate-leases it (cause ``straggler``) —
  first committed marker wins, the fencing rejects the loser;
- **poison workers**: a worker that reports failures on N distinct
  shards is drained (its lease requests refused, held leases
  redistributed), mirroring PR 2's poison-shard quarantine at worker
  granularity; a shard failed by several distinct workers is
  quarantined like the single-process path would.

The lease/epoch/heartbeat/reassignment state machine itself lives in
``parallel/leases.py`` as the generic :class:`~tmr_tpu.parallel.leases.
LeaseService`: this coordinator is its first client (map shards), the
serve fleet (serve/fleet.py) its second (traffic partitions). The
extraction changed NOTHING observable here — same counters, same
records, same grant discipline — pinned by the ``--elastic`` chaos
gauntlet.

The final stats table folds one float64 contribution per shard in
shard-list order — exactly the single-process fold — so an elastic run
over any number of workers, kills, and reassignments produces a
**byte-identical** table (scripts/chaos_probe.py --elastic proves it
under kill -9 and SIGSTOP). Everything is accounted in one validated
``elastic_report/v1`` document (diagnostics.validate_elastic_report).

Env knobs (all lazily read, registered in config.ENV_KNOBS):
``TMR_ELASTIC_TTL_S``, ``TMR_ELASTIC_HB_S``, ``TMR_ELASTIC_CHECK_S``,
``TMR_ELASTIC_STRAGGLER_FACTOR``, ``TMR_ELASTIC_STRAGGLER_MIN_S``,
``TMR_ELASTIC_MAX_REASSIGNS``, ``TMR_ELASTIC_POISON_FAILURES``,
``TMR_ELASTIC_CONNECT_TIMEOUT_S`` (every protocol dial — a black-holed
coordinator address fails a worker fast instead of hanging it in
``hello`` on the OS default connect timeout).

Import-light on purpose: nothing here imports jax at module load — the
worker pulls mapreduce (and through it jax) lazily, so the coordinator
can run on a box with no accelerator stack at all.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import socketserver
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from tmr_tpu.diagnostics import (
    ELASTIC_REPORT_SCHEMA,
    validate_elastic_report,
)
from tmr_tpu import obs
from tmr_tpu.obs import fleetobs as _fleetobs
from tmr_tpu.parallel.journal import (
    ShardJournal,
    StaleLeaseError,
    shard_stem,
)
from tmr_tpu.parallel.leases import (
    Lease,
    LeasePolicy,
    LeaseService,
    Resource,
    connect_timeout,
    oneshot,
    recv_line,
    send_line,
)
from tmr_tpu.utils import faults
from tmr_tpu.utils.atomicio import atomic_write

#: schema tag stamped on every lease record under ``_leases/``
LEASE_SCHEMA = "lease/v1"

# protocol helpers shared with the fleet client (parallel/leases.py);
# the old private names stay importable
_send_line = send_line
_recv_line = recv_line


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class ElasticPolicy:
    """Liveness / straggler / poison knobs for one elastic run.

    ``lease_ttl_s`` is the heartbeat budget: a lease not heartbeated for
    this long is revoked and its shard reassigned. ``hb_interval_s`` is
    the worker's beat cadence (default TTL/4 so one dropped beat never
    revokes). ``straggler_factor`` scales the rolling median of
    completed shard wall times into the speculative-re-execution bound
    (0 disables); ``straggler_min_done`` completed shards are required
    before the median means anything. ``max_reassigns`` bounds how many
    times one shard may bounce before it is quarantined outright;
    ``poison_failures`` distinct failed shards drain a worker;
    ``shard_fail_workers`` distinct workers failing one shard quarantine
    the shard (the deterministic-poison-data verdict)."""

    lease_ttl_s: float = 10.0
    hb_interval_s: float = 2.5
    check_interval_s: float = 1.0
    straggler_factor: float = 3.0
    straggler_min_s: float = 5.0
    straggler_min_done: int = 3
    max_reassigns: int = 4
    poison_failures: int = 3
    shard_fail_workers: int = 2

    @classmethod
    def from_env(cls, **overrides) -> "ElasticPolicy":
        """Resolve defaults from the TMR_ELASTIC_* env knobs (read
        lazily, at call time), then apply explicit overrides."""
        ttl = _env_float("TMR_ELASTIC_TTL_S", 10.0)
        base = dict(
            lease_ttl_s=ttl,
            hb_interval_s=_env_float("TMR_ELASTIC_HB_S", ttl / 4.0),
            check_interval_s=_env_float("TMR_ELASTIC_CHECK_S", ttl / 10.0),
            straggler_factor=_env_float("TMR_ELASTIC_STRAGGLER_FACTOR", 3.0),
            straggler_min_s=_env_float("TMR_ELASTIC_STRAGGLER_MIN_S", 5.0),
            max_reassigns=_env_int("TMR_ELASTIC_MAX_REASSIGNS", 4),
            poison_failures=_env_int("TMR_ELASTIC_POISON_FAILURES", 3),
        )
        base.update(overrides)
        return cls(**base)

    def lease_policy(self) -> LeasePolicy:
        """This policy in the generic LeaseService vocabulary."""
        return LeasePolicy(
            lease_ttl_s=self.lease_ttl_s,
            hb_interval_s=self.hb_interval_s,
            check_interval_s=self.check_interval_s,
            straggler_factor=self.straggler_factor,
            straggler_min_s=self.straggler_min_s,
            straggler_min_done=self.straggler_min_done,
            max_reassigns=self.max_reassigns,
            poison_failures=self.poison_failures,
            resource_fail_workers=self.shard_fail_workers,
        )


# --------------------------------------------------------- coordinator state
class _Shard(Resource):
    """A map shard as a leasable resource: the generic lease fields plus
    the map payload (path, category, the committed journal entry)."""

    __slots__ = ("path", "category", "stem", "entry", "images")

    def __init__(self, index: int, path: str, category: int):
        super().__init__(index, os.path.basename(path))
        self.path = path
        self.category = category
        self.stem = shard_stem(os.path.basename(path))
        self.entry: Optional[dict] = None
        self.images = 0


class _Handler(socketserver.StreamRequestHandler):
    """One thread per connection; JSON lines in, JSON lines out. The
    first ``hello`` marks the connection as a worker's control channel —
    EOF on a control channel with leases still held is the kill -9
    signature and triggers immediate reassignment."""

    def handle(self):  # noqa: D102 — protocol loop
        coord = self.server.coordinator  # type: ignore[attr-defined]
        control_worker = None
        clean = False
        try:
            while True:
                try:
                    msg = _recv_line(self.rfile)
                except (OSError, ValueError):
                    break
                if msg is None:
                    break
                if msg.get("op") == "hello":
                    control_worker = msg.get("worker")
                if msg.get("op") == "bye":
                    clean = True
                reply = coord.dispatch(msg)
                try:
                    _send_line(self.connection, reply)
                except OSError:
                    break
                if clean:
                    break
        finally:
            if control_worker is not None:
                coord.control_closed(control_worker, clean=clean)


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ElasticCoordinator:
    """Owns the shard queue as epoch-fenced leases and serves the worker
    protocol. The lease/liveness state machine is a
    :class:`~tmr_tpu.parallel.leases.LeaseService` (``self._svc``) —
    all mutable run state lives behind ITS lock; socket I/O and
    fault-point firing happen outside it."""

    def __init__(
        self,
        shard_paths: Sequence[str],
        journal_dir: str,
        *,
        features_out: Optional[str] = None,
        data_dir: Optional[str] = None,
        image_size: int = 1024,
        batch_size: int = 8,
        resume: bool = False,
        policy: Optional[ElasticPolicy] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        from tmr_tpu.parallel.mapreduce import category_of

        self.policy = policy or ElasticPolicy.from_env()
        self.journal = ShardJournal(journal_dir)
        self.lease_dir = os.path.join(self.journal.directory, "_leases")
        os.makedirs(self.lease_dir, exist_ok=True)
        # like the shard paths: workers resolve this from their own cwd,
        # so a relative features tree would scatter across worker cwds
        # (a tcp:// sink target is location-independent and must NOT be
        # mangled into a filesystem path)
        self.features_out = (
            features_out if not features_out
            or str(features_out).startswith("tcp://")
            else os.path.abspath(features_out)
        )
        # the cleanup sinks build ONCE (for a tcp:// target each
        # make_feature_sinks call would dial its own connection per
        # sweep pass and abandon it — connection churn at the sink)
        self._feature_sinks = make_feature_sinks(self.features_out)
        self.data_dir = data_dir
        self.image_size = int(image_size)
        self.batch_size = int(batch_size)
        self._host, self._port = host, int(port)
        self._lock = threading.RLock()
        # workers may run in any cwd on any host sharing the filesystem —
        # a lease must hand them a path that resolves from anywhere
        self._shards = [
            _Shard(i, os.path.abspath(p), category_of(p))
            for i, p in enumerate(shard_paths)
        ]
        stems = [s.stem for s in self._shards]
        if len(set(stems)) != len(stems):
            raise ValueError(
                "duplicate shard journal keys cannot be leased "
                "unambiguously; rename the shards"
            )
        self._svc = LeaseService(
            self._shards, self.policy.lease_policy(),
            metrics_prefix="elastic", noun="shard", key_field="shard",
            on_transition=self._on_transition,
        )
        self._server: Optional[_Server] = None
        self._server_thread: Optional[threading.Thread] = None
        self._monitor_thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        # fleet observability plane (TMR_FLEET_OBS): None when off —
        # instrumented ops below pay one `is None` check
        self._fleetobs: Optional[_fleetobs.FleetObs] = (
            _fleetobs.FleetObs(hb_interval_s=self.policy.hb_interval_s)
            if _fleetobs.fleet_obs_enabled() else None
        )
        if resume:
            for shard in self._shards:
                entry = self.journal.done(os.path.basename(shard.path))
                if entry is not None:
                    with self._svc.lock:
                        shard.entry = entry
                        shard.images = int(entry.get("images", 0))
                        self._svc.mark_resumed(
                            shard.index, worker=entry.get("worker"),
                            epoch=entry.get("epoch"),
                        )

    def _on_transition(self, shard: _Shard, lease: Lease,
                       state: str) -> None:
        """LeaseService transition hook (fires under the service lock):
        the durable lease record tracks held/revoked/committed/failed;
        quarantine invalidates the journal marker — the feature-tree
        removal is deferred to :meth:`_sweep_quarantined` (an rmtree
        here would hold the protocol lock through disk I/O and stall
        every worker's heartbeat)."""
        if state == "quarantined":
            self.journal.invalidate(os.path.basename(shard.path))
            return
        self._write_lease(shard, lease, state)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        """Bind the server + liveness monitor; returns (host, port)."""
        server = _Server((self._host, self._port), _Handler)
        server.coordinator = self  # type: ignore[attr-defined]
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            name="elastic-coordinator", daemon=True,
        )
        monitor_thread = threading.Thread(
            target=self._monitor_loop, name="elastic-monitor", daemon=True,
        )
        with self._lock:
            self._server = server
            self._server_thread = server_thread
            self._monitor_thread = monitor_thread
        # wall_s measures serving: the resume journal scan in __init__
        # (and any caller delay before start) must not count
        self._svc.restart_clock()
        server_thread.start()
        monitor_thread.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            assert self._server is not None, "coordinator not started"
            return self._server.server_address[:2]

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every shard is settled (committed / resumed /
        quarantined); True when it happened within ``timeout``. A
        settled wait also runs the quarantine feature sweep, so disk
        reconciles with the table before the caller reads either."""
        done = self._svc.done_event.wait(timeout)
        if done:
            self._sweep_quarantined()
        return done

    def stop(self) -> None:
        self._stop_event.set()
        with self._lock:
            server = self._server
            monitor = self._monitor_thread
        if server is not None:
            server.shutdown()
            server.server_close()
        if monitor is not None:
            monitor.join(timeout=5.0)

    # ------------------------------------------------------------- protocol
    def dispatch(self, msg: dict) -> dict:
        op = msg.get("op")
        handler = {
            "hello": self._op_hello,
            "lease": self._op_lease,
            "heartbeat": self._op_heartbeat,
            "precommit": self._op_precommit,
            "commit": self._op_commit,
            "fail": self._op_fail,
            "bye": self._op_bye,
            "state": lambda m: self.state(),
        }.get(op)
        if handler is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            return handler(msg)
        except Exception as e:  # protocol must answer, never wedge
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _op_hello(self, msg: dict) -> dict:
        # a fresh hello clears a prior incarnation's departure flags
        # (stable worker ids may reconnect); a drained worker stays
        # drained
        self._svc.rejoin(str(msg.get("worker")))
        return {
            "ok": True,
            "journal_dir": self.journal.directory,
            "features_out": self.features_out,
            "data_dir": self.data_dir,
            "image_size": self.image_size,
            "batch_size": self.batch_size,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
            "shards": len(self._shards),
        }

    def _op_lease(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        wait = {"shard": None,
                "wait_s": max(self.policy.check_interval_s, 0.05)}
        verdict, shard, epoch = self._svc.select(wid)
        if verdict == "drained":
            return {"shard": None, "drained": True}
        if verdict == "done":
            return {"shard": None, "done": True}
        if verdict != "grant":
            return wait
        # the lease fault point fires OUTSIDE the lock (latency specs
        # sleep here); an injected grant failure re-queues the shard
        try:
            with faults.shard_scope(shard.index, epoch):
                faults.fire("lease")
        except Exception as e:
            self._svc.requeue(shard)
            wait = dict(wait)
            wait["error"] = f"{type(e).__name__}: {e}"
            return wait
        if self._svc.install(shard, epoch, wid) is None:
            return wait  # committed while we were firing faults
        grant = {
            "shard": shard.path,
            "index": shard.index,
            "epoch": epoch,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
        }
        if self._fleetobs is not None:
            # the lease grant is this protocol's front door: ONE trace
            # id minted here follows the shard through every
            # heartbeat/precommit/commit hop (instant root anchor span)
            root = _fleetobs.root_span(
                "elastic.grant", shard=os.path.basename(shard.path),
                index=shard.index, epoch=epoch, worker=wid,
            )
            grant["ctx"] = root.ctx()
            root.close()
        return grant

    def _op_heartbeat(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        fo = self._fleetobs
        if fo is None:
            if not self._svc.heartbeat(wid, index, epoch):
                return {"ok": False, "cause": "stale_epoch"}
            return {"ok": True}
        # liveness + rollup fold under the propagated lease trace; the
        # reply stamps OUR clock for the worker's midpoint offset sample
        with _fleetobs.op_span(msg, "elastic.heartbeat", worker=wid,
                               index=index):
            fo.note_beat(wid)
            att = msg.get("obs")
            if att is not None:
                fo.fold(wid, att)
            fresh = self._svc.heartbeat(wid, index, epoch)
        if not fresh:
            return {"ok": False, "cause": "stale_epoch",
                    "obs_ts": time.perf_counter()}
        return {"ok": True, "obs_ts": time.perf_counter()}

    def _op_precommit(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        with _fleetobs.op_span(msg, "elastic.precommit", worker=wid,
                               index=index):
            with self._svc.lock:
                if self._svc.current_lease(index, epoch, wid) is None:
                    self._svc.record_fence(index, wid, epoch,
                                           "precommit")
                    return {"ok": False, "cause": "stale_epoch"}
                return {"ok": True}

    def _op_commit(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        entry = msg.get("entry")
        with _fleetobs.op_span(msg, "elastic.commit", worker=wid,
                               index=index):
            with self._svc.lock:
                if self._svc.current_lease(index, epoch, wid) is None \
                        or not isinstance(entry, dict):
                    self._svc.record_fence(index, wid, epoch, "commit")
                    self._invalidate_stale_marker(index, epoch)
                    return {"ok": False, "cause": "stale_epoch"}
                shard, _lease = self._svc.commit(wid, index, epoch)
                shard.entry = entry
                shard.images = int(entry.get("images", 0))
                return {"ok": True}

    def _invalidate_stale_marker(self, index: int, epoch: int) -> None:
        """A stale writer that slipped a marker to disk in the
        precommit/commit race window must not leave it vouching. When
        the shard IS committed, the fix is a rewrite, not an unlink: the
        coordinator re-stamps the WINNER's accepted entry (it holds the
        full payload) so a committed shard always keeps a valid marker
        for crash-resume — unlinking would trade one corruption for
        another. Only an unsettled shard's stale marker is dropped."""
        if not (0 <= index < len(self._shards)):
            return
        shard = self._shards[index]
        name = os.path.basename(shard.path)
        entry = self.journal.done(name)
        if entry is None or entry.get("epoch") != epoch \
                or epoch == shard.epoch:
            return
        if shard.status == "committed" and shard.entry is not None:
            win = shard.entry
            self.journal.record(
                name, category=win["category"], sums=win["sums"],
                images=win.get("images", 0),
                skipped_images=win.get("skipped_images", 0),
                skipped_members=win.get("skipped_members", 0),
                nonfinite_images=win.get("nonfinite_images", 0),
                attempts=win.get("attempts", 1),
                wall_s=win.get("wall_s", 0.0),
                worker=shard.worker, epoch=shard.epoch,
            )
        else:
            self.journal.invalidate(name)

    def _op_fail(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        causes = msg.get("causes") or []
        res = self._svc.fail(wid, index, epoch, causes)
        if res["stale"]:
            return {"ok": True, "stale": True}
        return {"ok": True, "drained": res["drained"]}

    def _op_bye(self, msg: dict) -> dict:
        fo = self._fleetobs
        if fo is not None and msg.get("obs") is not None:
            # end-of-life flush: the leaver's final registry totals (+
            # trace/flight tail) land before its state disappears
            fo.fold(str(msg.get("worker")), msg.get("obs"), final=True)
        self._svc.bye(str(msg.get("worker")))
        return {"ok": True}

    def control_closed(self, wid: str, clean: bool) -> None:
        """The worker's control connection ended. A dirty close (no
        ``bye``) with leases held is a crashed/killed worker — reassign
        everything it was running immediately."""
        self._svc.control_closed(str(wid), clean)

    # ------------------------------------------------------------- liveness
    def _sweep_quarantined(self) -> None:
        """Remove quarantined shards' feature files — the coordinator is
        the ONLY party allowed to do this (workers cannot tell their own
        stale failure from another worker's success). Runs OUTSIDE the
        protocol lock (rmtree on a big tree must not stall heartbeats);
        the monitor calls it every pass and ``wait`` once more at
        settle. Best-effort: feature writes are idempotent but unfenced,
        so a paused writer resuming after the sweep can recreate files —
        the journal fence keeps the TABLE exact regardless."""
        targets = self._svc.take_cleanup_targets()
        if not targets:
            return
        _save, cleanup, _sync = self._feature_sinks
        if cleanup is None:
            return
        for shard in targets:
            try:
                cleanup(os.path.basename(shard.path))
            except Exception:
                pass

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(self.policy.check_interval_s):
            if not self._svc.done_event.is_set():
                self._monitor_pass()
            self._sweep_quarantined()  # outside the protocol lock

    def _monitor_pass(self) -> None:
        self._svc.expire_pass()
        candidate = self._svc.elect_straggler()
        if candidate is None:
            return
        shard, lease = candidate
        try:
            # speculative duplicate election — its own fault point,
            # fired outside the lock (latency specs sleep)
            with faults.shard_scope(shard.index, lease.epoch):
                faults.fire("steal")
        except Exception:
            self._svc.veto_steal(shard)
            return
        self._svc.confirm_steal(shard, lease)

    def _write_lease(self, shard: _Shard, lease: Lease,
                     state: str) -> None:
        """The durable lease record (atomic, not fsynced — on a
        coordinator crash the journal is the source of truth; leases
        only need to never be half-written)."""
        doc = {
            "schema": LEASE_SCHEMA,
            "shard": os.path.basename(shard.path),
            "index": shard.index,
            "worker": lease.worker,
            "epoch": lease.epoch,
            "granted_at": lease.granted_at,
            "expires_at": lease.expires_at,
            "hb": lease.hb,
            "state": state,
        }
        path = os.path.join(self.lease_dir, shard.stem + ".json")
        try:
            atomic_write(path, lambda f: json.dump(doc, f), fsync=False)
        except OSError:
            pass  # lease records are advisory; memory state is canonical

    # ------------------------------------------------------------- results
    def table(self) -> np.ndarray:
        """The folded (4, 5) stats table — one float64 addition per
        settled shard in shard-list order, the single-process fold, so
        the result is byte-identical to a fault-free ``run_stream``."""
        from tmr_tpu.parallel.mapreduce import StatAccumulator

        acc = StatAccumulator()
        with self._svc.lock:
            for shard in self._shards:
                if shard.entry is not None and shard.status in (
                    "committed", "resumed"
                ):
                    acc.add_totals(shard.category, shard.entry["sums"])
        return acc.table

    def state(self) -> dict:
        """Mid-run introspection for probes/tests (NOT the report): held
        leases, live tallies, settled counts."""
        with self._svc.lock:
            out = {
                "ok": True,
                "settled": self._svc.settled_count,
                "shards": len(self._shards),
                "pending": self._svc.pending_snapshot(),
                "leases": {
                    shard.index: [
                        {"worker": l.worker, "epoch": l.epoch, "hb": l.hb}
                        for l in shard.leases.values()
                    ]
                    for shard in self._shards if shard.leases
                },
                "statuses": {
                    os.path.basename(s.path): s.status
                    for s in self._shards
                },
                "reassignments": [dict(r)
                                  for r in self._svc.reassignments],
                "fenced_rejections": [dict(r) for r in self._svc.fenced],
                "workers": {
                    w.wid: {"committed": w.committed,
                            "failed": sorted(w.failed),
                            "drained": w.drained, "dead": w.dead}
                    for w in self._svc.workers.values()
                },
            }
        # outside the service lock; disabled state() stays
        # byte-identical — no key at all
        if self._fleetobs is not None:
            out["fleet_metrics"] = self._fleetobs.state()
        return out

    @property
    def fleet_obs(self) -> Optional[_fleetobs.FleetObs]:
        """The coordinator-side observability plane (None when
        TMR_FLEET_OBS is off)."""
        return self._fleetobs

    def report(self) -> dict:
        """The final ``elastic_report/v1`` document (call after
        :meth:`wait`; diagnostics.validate_elastic_report checks it,
        including the exact totals reconciliation)."""
        with self._svc.lock:
            shards = [{
                "index": s.index,
                "shard": os.path.basename(s.path),
                "category": int(s.category),
                "status": s.status,
                "worker": s.worker,
                "epoch": s.epoch,
                "assignments": s.assignments,
                "failures": [dict(f) for f in s.failures],
                "images": s.images,
                "wall_s": round(s.wall_s, 6),
            } for s in self._shards]
            workers = {
                w.wid: {
                    "committed": w.committed,
                    "failed_shards": sorted(w.failed),
                    "drained": w.drained,
                    "dead": w.dead,
                } for w in self._svc.workers.values()
            }
            totals = {
                "shards": len(self._shards),
                "committed": sum(
                    1 for s in self._shards if s.status == "committed"
                ),
                "resumed": sum(
                    1 for s in self._shards if s.status == "resumed"
                ),
                "quarantined": sum(
                    1 for s in self._shards if s.status == "quarantined"
                ),
                "reassignments": len(self._svc.reassignments),
                "fenced_rejections": len(self._svc.fenced),
                "workers": len(self._svc.workers),
                "drained_workers": sum(
                    1 for w in self._svc.workers.values() if w.drained
                ),
                "wall_s": round(self._svc.run_wall_s(), 6),
            }
            doc = {
                "schema": ELASTIC_REPORT_SCHEMA,
                "shards": shards,
                "workers": workers,
                "reassignments": [dict(r)
                                  for r in self._svc.reassignments],
                "fenced_rejections": [dict(r) for r in self._svc.fenced],
                "quarantined": [
                    os.path.basename(s.path) for s in self._shards
                    if s.status == "quarantined"
                ],
                "resumed": [
                    os.path.basename(s.path) for s in self._shards
                    if s.status == "resumed"
                ],
                "totals": totals,
                "metrics": obs.get_registry().snapshot(),
            }
        return doc

    def write_report(self, path: str) -> dict:
        doc = self.report()
        problems = validate_elastic_report(doc)
        if problems:  # emit-then-validate: never write a broken document
            raise ValueError(
                f"elastic_report failed validation: {problems}"
            )

        def dump(f):
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

        atomic_write(path, dump)
        return doc


# ----------------------------------------------------------------- worker
class WorkerClient:
    """The worker side of the protocol: one persistent control
    connection for lease/commit/fail (serial request/response) plus
    fresh one-shot connections for heartbeats. Thread-safe — the lock
    serializes the control socket. The DIAL is bounded by
    ``TMR_ELASTIC_CONNECT_TIMEOUT_S`` (leases.connect_timeout) so a
    black-holed coordinator address fails fast; ``timeout`` bounds each
    exchange once connected."""

    def __init__(self, address: Tuple[str, int], worker_id: str,
                 timeout: float = 30.0):
        self.address = (address[0], int(address[1]))
        self.worker_id = worker_id
        self._lock = threading.Lock()
        self._sock = socket.create_connection(
            self.address, timeout=connect_timeout(min(timeout, 5.0))
        )
        self._sock.settimeout(timeout)
        self._file = self._sock.makefile("rb")
        # fleet observability plane (TMR_FLEET_OBS): metrics deltas +
        # spans ride heartbeats, lease-grant ctx rides every fenced op
        self._obs: Optional[_fleetobs.WorkerObs] = (
            _fleetobs.WorkerObs()
            if _fleetobs.fleet_obs_enabled() else None
        )
        self._lease_ctx: dict = {}  # (index, epoch) -> wire ctx
        self.config = self._call({"op": "hello"})

    def _ctx_for(self, index: int, epoch: int) -> Optional[dict]:
        if self._obs is None:
            return None
        with self._lock:
            return self._lease_ctx.get((int(index), int(epoch)))

    def _stamp_ctx(self, doc: dict, index: int, epoch: int) -> dict:
        ctx = self._ctx_for(index, epoch)
        if ctx is not None:
            doc["ctx"] = ctx
        return doc

    def _call(self, doc: dict) -> dict:
        doc = dict(doc)
        doc.setdefault("worker", self.worker_id)
        with self._lock:
            _send_line(self._sock, doc)
            reply = _recv_line(self._file)
        if reply is None:
            raise ConnectionError("coordinator closed the connection")
        return reply

    def lease(self) -> dict:
        grant = self._call({"op": "lease"})
        if self._obs is not None and grant.get("index") is not None:
            ctx = _fleetobs.ctx_of(grant)
            if ctx is not None:
                with self._lock:
                    self._lease_ctx[(int(grant["index"]),
                                     int(grant["epoch"]))] = ctx
        return grant

    def heartbeat(self, index: int, epoch: int) -> dict:
        """One beat on a fresh connection (never blocks the control
        channel; a killed worker's missing beats are the liveness
        signal)."""
        doc = {
            "op": "heartbeat", "worker": self.worker_id,
            "index": index, "epoch": epoch,
        }
        w_obs = self._obs
        t_send = 0.0
        if w_obs is not None:
            # bounded metrics/span delta + lease ctx ride the beat;
            # the stamped reply clock feeds offset estimation
            self._stamp_ctx(doc, index, epoch)
            doc["obs"] = w_obs.attachment()
            t_send = time.perf_counter()
        reply = oneshot(self.address, doc)
        if w_obs is not None:
            w_obs.clock_sample(t_send, reply.get("obs_ts"),
                               time.perf_counter())
        return reply

    def precommit(self, index: int, epoch: int) -> dict:
        return self._call(self._stamp_ctx(
            {"op": "precommit", "index": index, "epoch": epoch},
            index, epoch,
        ))

    def commit(self, index: int, epoch: int, entry: dict) -> dict:
        reply = self._call(self._stamp_ctx(
            {"op": "commit", "index": index, "epoch": epoch,
             "entry": entry},
            index, epoch,
        ))
        if self._obs is not None:
            with self._lock:
                self._lease_ctx.pop((int(index), int(epoch)), None)
        return reply

    def fail(self, index: int, epoch: int, causes: List[dict]) -> dict:
        return self._call({"op": "fail", "index": index, "epoch": epoch,
                           "causes": causes})

    def close(self) -> None:
        bye = {"op": "bye", "worker": self.worker_id}
        if self._obs is not None:
            # end-of-life flush: final totals + remaining spans ride
            # the bye so a short-lived worker still reconciles
            bye["obs"] = self._obs.attachment(final=True)
        with self._lock:
            try:
                _send_line(self._sock, bye)
                self._file.readline()
            except OSError:
                pass
            try:
                self._file.close()
                self._sock.close()
            except OSError:
                pass


class LeasedJournal(ShardJournal):
    """ShardJournal whose every commit is fenced on the worker's CURRENT
    lease epoch: ``record`` round-trips a precommit to the coordinator
    and raises :class:`StaleLeaseError` when the epoch was revoked —
    before any marker byte touches disk."""

    def __init__(self, directory: str, client: WorkerClient):
        super().__init__(directory)
        self._client = client
        self._fence_lock = threading.Lock()
        self._index: Optional[int] = None
        self._epoch: Optional[int] = None

    def set_lease(self, index: int, epoch: int) -> None:
        with self._fence_lock:
            self._index, self._epoch = index, epoch

    def record(self, shard_name, *args, **kw):  # noqa: D102
        with self._fence_lock:
            index, epoch = self._index, self._epoch

        def fence():
            reply = self._client.precommit(index, epoch)
            if not reply.get("ok"):
                raise StaleLeaseError(
                    f"lease for shard {shard_name!r} epoch {epoch} was "
                    f"revoked ({reply.get('cause', 'stale_epoch')}) — "
                    "commit fenced"
                )

        kw.setdefault("worker", self._client.worker_id)
        kw.setdefault("epoch", epoch)
        kw.setdefault("fence", fence)
        return super().record(shard_name, *args, **kw)

    def invalidate(self, shard_name: str) -> None:
        """No-op ON PURPOSE: marker-invalidation authority stays with
        the coordinator. The executor invalidates on local quarantine —
        but a worker quarantined by the fence CANNOT tell its own stale
        failure from another worker's success, so letting it unlink the
        marker would delete the winner's valid commit (the same reason
        workers get cleanup_features=None)."""


def make_feature_sinks(features_out: Optional[str]):
    """(save, cleanup, sync) callables writing per-image feature
    ``.npy`` under ``features_out/<category>/<shard>/`` — the ONE
    definition of that layout: the mapreduce CLI and elastic workers
    both call this, so single-process and elastic runs produce
    byte-identical trees by construction. All None when features are
    off.

    A ``tcp://host:port`` target streams features over the fleet
    data-link JSON-lines protocol into a serve-side
    ``serve.gallery.FeatureSinkServer`` instead (the deferred half of
    PR 10's elastic item: extracted features land in the serve feature
    cache / gallery index directly, no ``.npy`` bounce) — see
    :func:`_network_feature_sinks` for the durability contract."""
    if not features_out:
        return None, None, None
    if str(features_out).startswith("tcp://"):
        return _network_feature_sinks(str(features_out))
    from tmr_tpu.parallel.mapreduce import (
        CATEGORIES, atomic_save_npy, category_of,
    )
    from tmr_tpu.utils.atomicio import fsync_dir

    def shard_dir(shard: str) -> str:
        cat = CATEGORIES[category_of(shard)]
        return os.path.join(features_out, cat, shard.replace(".tar", ""))

    def save(shard: str, name: str, feat) -> None:
        d = shard_dir(shard)
        os.makedirs(d, exist_ok=True)
        base = os.path.splitext(os.path.basename(name))[0]
        atomic_save_npy(os.path.join(d, base + ".npy"), feat)

    def cleanup(shard: str) -> None:
        import shutil

        shutil.rmtree(shard_dir(shard), ignore_errors=True)

    def sync(shard: str) -> None:
        fsync_dir(shard_dir(shard))

    return save, cleanup, sync


def _network_feature_sinks(url: str):
    """(save, cleanup, sync) streaming over the fleet data-link
    protocol to a ``serve.gallery.FeatureSinkServer`` at
    ``tcp://host:port`` — extracted features flow straight into the
    serve feature cache / gallery index, never through ``.npy`` files.

    Durability keeps the ``atomic_save_npy``-before-journal contract on
    the wire: ``save`` pipelines feature lines with NO per-image ack,
    and ``sync`` (called by ``_run_stream_impl`` before the shard's
    journal marker commits) round-trips one ack that vouches for every
    feature sent before it on the same ordered TCP connection — a
    dirty ack (or any socket error) RAISES, failing the shard attempt
    so the existing retry machinery re-streams the whole shard. One
    lazily-dialed persistent connection per process, reset on error;
    ``cleanup`` is the coordinator's quarantine eviction."""
    rest = url[len("tcp://"):]
    host, _, port_s = rest.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"feature-sink url {url!r}: expected tcp://host:port"
        )
    if not host:
        raise ValueError(
            f"feature-sink url {url!r}: expected tcp://host:port"
        )
    from tmr_tpu.serve.fleet import pack_array

    state = {"sock": None, "file": None}
    lock = threading.Lock()

    def _drop_locked() -> None:
        for key in ("file", "sock"):
            obj, state[key] = state[key], None
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass

    def _conn_locked():
        if state["sock"] is None:
            sock = socket.create_connection(
                (host, port), timeout=connect_timeout()
            )
            # generous exchange timeout: a dead sink must FAIL the
            # shard attempt (retryable), never wedge the worker — the
            # same philosophy as the map phase's stall timeout
            sock.settimeout(60.0)
            f = sock.makefile("rb")
            state["sock"], state["file"] = sock, f
            send_line(sock, {"op": "hello", "worker": f"map-{os.getpid()}"})
            reply = recv_line(f)
            if not (reply and reply.get("ok")):
                _drop_locked()
                raise ConnectionError(
                    f"feature sink {host}:{port} refused hello: {reply!r}"
                )
        return state["sock"], state["file"]

    def _exchange(doc: dict, want_ack: bool) -> Optional[dict]:
        with lock:
            try:
                sock, f = _conn_locked()
                send_line(sock, doc)
                if not want_ack:
                    return None
                reply = recv_line(f)
            except (OSError, ValueError) as e:
                _drop_locked()
                raise ConnectionError(
                    f"feature sink {host}:{port} unreachable: {e}"
                ) from e
            if reply is None:
                _drop_locked()
                raise ConnectionError(
                    f"feature sink {host}:{port} closed mid-exchange"
                )
            return reply

    def save(shard: str, name: str, feat) -> None:
        base = os.path.splitext(os.path.basename(name))[0]
        _exchange({
            "op": "feature",
            "shard": shard_stem(shard),
            "name": base,
            "array": pack_array(feat),
        }, want_ack=False)

    def cleanup(shard: str) -> None:
        _exchange({"op": "evict", "shard": shard_stem(shard)},
                  want_ack=True)

    def sync(shard: str) -> None:
        reply = _exchange({"op": "sync", "shard": shard_stem(shard)},
                          want_ack=True)
        if not reply.get("ok"):
            # drop the connection before failing the attempt: the
            # retry must start from a FRESH dial, not inherit any
            # half-streamed connection state
            with lock:
                _drop_locked()
            raise ConnectionError(
                f"feature sink {host}:{port} reported "
                f"{reply.get('errors')} failed features for {shard}"
            )

    return save, cleanup, sync


def stub_encode_stats_fn(delay_s: float = 0.0,
                         slow_shards: Sequence[str] = (),
                         slow_delay_s: float = 0.0,
                         fail_shards: Sequence[str] = ()) -> Callable:
    """A numpy-only encoder stand-in (no XLA compile — the
    test_overload stub-predictor pattern applied to the map phase):
    4x-decimated pixels minus 0.5 as 'features' plus the exact
    feature_stats math in float32 numpy. Deterministic, so a
    single-process run and any elastic run over the same shards produce
    byte-identical tables. ``delay_s`` sleeps per batch (paces shards so
    kills/stalls land mid-shard); ``slow_shards``/``fail_shards`` match
    on substrings of the current shard set by the worker loop via the
    returned fn's ``context`` attribute."""

    def encode(images):
        shard = getattr(encode, "context", "")
        if any(s in shard for s in fail_shards):
            raise RuntimeError(f"stub encoder poisoned for {shard!r}")
        d = delay_s + (
            slow_delay_s if any(s in shard for s in slow_shards) else 0.0
        )
        if d:
            time.sleep(d)
        arr = np.asarray(images, np.float32)
        feats = arr[:, ::4, ::4, :] - 0.5
        b = feats.shape[0]
        flat = feats.reshape(b, -1)
        mean = flat.mean(axis=1)
        std = np.sqrt(((flat - mean[:, None]) ** 2).mean(axis=1))
        mx = flat.max(axis=1)
        spar = (flat <= 0).mean(axis=1)
        stats = np.stack([mean, std, mx, spar], axis=1)
        return feats, stats

    encode.context = ""
    return encode


def run_worker(
    address: Tuple[str, int],
    worker_id: str,
    encode_stats_fn: Callable,
    *,
    retry=None,
    hb_path: Optional[str] = None,
    batch_size: Optional[int] = None,
    image_size: Optional[int] = None,
    features_out: Optional[str] = None,
    max_idle_s: float = 60.0,
) -> dict:
    """One worker's whole life: hello, then lease → run the shard
    through the unchanged ``_run_stream_impl`` attempt machinery →
    fenced commit (or fail report) → release, until the coordinator says
    done (or drains us). Returns a summary dict.

    The lease is heartbeated by an ``obs.flight.Heartbeat`` whose emit
    callable sends the beat (and logs it to ``hb_path`` JSONL when
    given) — the ``heartbeat`` fault point fires inside emit, so an
    injected latency stalls beats exactly like a SIGSTOP would."""
    from tmr_tpu.parallel.mapreduce import (
        MapReport, RetryPolicy, _load_shard_python, _run_stream_impl,
    )
    from tmr_tpu.obs.flight import Heartbeat
    from tmr_tpu.utils.profiling import log_progress, log_warning

    client = WorkerClient(address, worker_id)
    cfg = client.config
    journal = LeasedJournal(cfg["journal_dir"], client)
    feat_dir = features_out if features_out is not None \
        else cfg.get("features_out")
    # cleanup authority stays with the COORDINATOR: a worker whose local
    # attempt quarantines (a stale fence included) must never delete
    # feature files another worker may have just committed — the shard's
    # features are removed only if the coordinator itself quarantines it
    save, _cleanup_unused, sync = make_feature_sinks(feat_dir)
    batch = int(batch_size or cfg.get("batch_size") or 8)
    size = int(image_size or cfg.get("image_size") or 1024)
    hb_interval = float(cfg.get("hb_interval_s") or 2.5)
    retry = retry or RetryPolicy()
    summary = {"worker": worker_id, "committed": 0, "failed": 0,
               "fenced": 0, "leases": 0, "drained": False}
    idle_since: Optional[float] = None
    try:
        while True:
            try:
                grant = client.lease()
            except (ConnectionError, OSError) as e:
                # coordinator gone (run settled and it exited, or it
                # crashed) — a worker outliving it is normal, not fatal
                log_warning(
                    f"elastic worker {worker_id}: coordinator "
                    f"unreachable ({e}); exiting"
                )
                break
            if grant.get("done") or grant.get("drained"):
                summary["drained"] = bool(grant.get("drained"))
                break
            if grant.get("shard") is None:
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > max_idle_s:
                    log_warning(
                        f"elastic worker {worker_id}: idle past "
                        f"{max_idle_s}s with the run unfinished; exiting"
                    )
                    break
                time.sleep(float(grant.get("wait_s", 0.2)))
                continue
            idle_since = None
            summary["leases"] += 1
            path = grant["shard"]
            index, epoch = int(grant["index"]), int(grant["epoch"])
            shard_base = os.path.basename(path)
            grant_ctx = _fleetobs.ctx_of(grant)
            t_run0 = time.perf_counter() if grant_ctx is not None \
                else 0.0
            journal.set_lease(index, epoch)
            if hasattr(encode_stats_fn, "context"):
                encode_stats_fn.context = shard_base

            def emit(index=index, epoch=epoch, shard=shard_base):
                with faults.shard_scope(index, epoch):
                    faults.fire("heartbeat")
                reply = client.heartbeat(index, epoch)
                return {"worker": worker_id, "shard": shard,
                        "epoch": epoch, "ok": bool(reply.get("ok"))}

            hb = Heartbeat(
                emit,
                hb_path or os.path.join(
                    journal.directory, "_leases",
                    f"hb_{worker_id}.jsonl",
                ),
                interval_s=hb_interval,
            )
            report = MapReport()
            try:
                _run_stream_impl(
                    [path], encode_stats_fn, batch, size, save,
                    1, _load_shard_python, retry, journal, False, report,
                    cleanup_features=None, sync_features=sync,
                )
            finally:
                hb.stop(timeout=hb_interval + 5.0)
                if grant_ctx is not None:
                    # the worker's hop of the lease trace: the whole
                    # shard run, parented under the grant anchor
                    _fleetobs.add_remote_span(
                        "elastic.worker.shard", t_run0,
                        time.perf_counter(), grant_ctx,
                        worker=worker_id, shard=shard_base, epoch=epoch,
                    )
            rec = report.document()["shards"][0]
            if rec["status"] == "ok":
                entry = journal.done(shard_base)
                try:
                    reply = client.commit(index, epoch, entry)
                except (ConnectionError, OSError) as e:
                    log_warning(
                        f"elastic worker {worker_id}: coordinator "
                        f"unreachable at commit ({e}); exiting"
                    )
                    break
                if reply.get("ok"):
                    summary["committed"] += 1
                    log_progress(
                        f"elastic worker {worker_id}: committed "
                        f"{shard_base} (epoch {epoch})"
                    )
                else:
                    summary["fenced"] += 1  # lost the commit race
            elif any(
                "StaleLeaseError" in str(c.get("error", ""))
                for c in rec["causes"]
            ):
                # fenced at precommit — the coordinator already counted
                # the rejection and reassigned; nothing to report
                summary["fenced"] += 1
                log_progress(
                    f"elastic worker {worker_id}: fenced off "
                    f"{shard_base} (epoch {epoch}); moving on"
                )
            else:
                summary["failed"] += 1
                try:
                    client.fail(index, epoch, rec["causes"])
                except (ConnectionError, OSError) as e:
                    log_warning(
                        f"elastic worker {worker_id}: coordinator "
                        f"unreachable at fail report ({e}); exiting"
                    )
                    break
    finally:
        client.close()
    return summary
