"""Segment-anything surface: model registry, predictor, auto mask generator.

The reference vendors Meta's SAM package (utils/segment_anything/ — SURVEY
§2.1 #18: ``sam_model_registry``/``build_sam.py:47-52``, ``SamPredictor``
(predictor.py), ``SamAutomaticMaskGenerator`` (automatic_mask_generator.py),
with two local patches: the mask decoder auto-picks the best-IoU mask
(mask_decoder.py:100-103) and upsamples mismatched PEs). This module is the
TPU-native equivalent built from the framework's own components: the Flax
SamViT encoder (models/vit.py), PromptEncoder/MaskDecoder
(models/sam_decoder.py — best-IoU selection built in, matching the
reference's patch), SAM preprocessing (data/transforms.py), and the
fixed-capacity NMS ops.

Design differences from the vendored package, deliberately TPU-first:
- encode/decode are jitted programs cached per prompt-batch bucket — the
  predictor encodes an image once and serves any number of prompt queries
  from the cached embedding (predictor.py's set_image/predict contract);
- the automatic mask generator runs the point grid as *batched* prompt
  chunks through one decode program (no per-point Python loop) and dedupes
  with the framework's padded NMS instead of torchvision's.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.data.transforms import sam_longest_side_preprocess
from tmr_tpu.models.sam_decoder import (
    MaskDecoder,
    PromptEncoder,
    resize_align_corners,
)
from tmr_tpu.models.vit import build_sam_vit


class Sam:
    """Encoder + prompt encoder + mask decoder with one params tree."""

    def __init__(self, model_type: str = "vit_b", params: Optional[dict] = None,
                 image_size: int = 1024):
        self.model_type = model_type
        self.image_size = image_size
        self.image_encoder = build_sam_vit(model_type, dtype=jnp.bfloat16)
        self.prompt_encoder = PromptEncoder()
        self.mask_decoder = MaskDecoder()
        self.params = params

    def init_random(self, seed: int = 0) -> dict:
        """Random init (smoke/tests; the reference builds weightless too)."""
        import flax.linen as nn

        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        s = self.image_size
        enc = jax.jit(self.image_encoder.init)(
            k1, jnp.zeros((1, s, s, 3), jnp.float32)
        )["params"]

        def init_pe(module):
            module(jnp.zeros((1, 4)), (s, s), (4, 4))
            module.embed_points(
                jnp.zeros((1, 2, 2)), jnp.zeros((1, 2), jnp.int32), (s, s)
            )
            module.embed_masks(jnp.zeros((1, 16, 16, 1)))

        pe = nn.init(init_pe, self.prompt_encoder)(k2)["params"]
        d = self.mask_decoder.transformer_dim
        md = self.mask_decoder.init(
            k3, jnp.zeros((1, 4, 4, d)), jnp.zeros((4, 4, d)),
            jnp.zeros((1, 2, d)), jnp.zeros((1, 4, 4, d)),
        )["params"]
        self.params = {"image_encoder": enc, "prompt_encoder": pe,
                       "mask_decoder": md}
        return self.params

    @classmethod
    def from_checkpoint(cls, path: str, model_type: str = "vit_b") -> "Sam":
        """Build from a full SAM/SAM-HQ .pth (build_sam.py registry role)."""
        from tmr_tpu.utils.convert import (
            convert_mask_decoder,
            convert_prompt_encoder,
            convert_sam_vit,
            load_torch_state_dict,
        )

        sd = load_torch_state_dict(path)
        params = {
            "image_encoder": convert_sam_vit(sd, "image_encoder."),
            "prompt_encoder": convert_prompt_encoder(sd),
            "mask_decoder": convert_mask_decoder(sd),
        }
        return cls(model_type, params=params)


# build_sam.py:47-52 registry equivalent
sam_model_registry: Dict[str, object] = {
    "vit_b": partial(Sam, "vit_b"),
    "vit_h": partial(Sam, "vit_h"),
    "default": partial(Sam, "vit_h"),
}


class SamPredictor:
    """Encode an image once; answer point/box prompt queries from the cached
    embedding (predictor.py:26-269 contract). Returns the best-IoU mask per
    prompt — the reference's patched decoder behavior."""

    def __init__(self, sam: Sam):
        self.sam = sam
        if sam.params is None:
            raise ValueError("Sam has no params; call init_random() or "
                             "from_checkpoint() first")
        self._encode = jax.jit(
            lambda p, x: sam.image_encoder.apply({"params": p}, x)
        )
        self._decode_cache: dict = {}
        self.reset_image()

    def reset_image(self):
        self.features = None
        self.orig_hw: Optional[Tuple[int, int]] = None
        self.scale: float = 1.0

    def set_image(self, image: np.ndarray) -> None:
        """image: (H, W, 3) uint8 RGB. Preprocess (resize longest side to
        1024, SAM normalize, pad) + one jitted encoder pass."""
        image = np.asarray(image)
        self.orig_hw = image.shape[:2]
        self.scale = self.sam.image_size / max(self.orig_hw)
        x = sam_longest_side_preprocess(image, self.sam.image_size)
        self.features = self._encode(self.sam.params["image_encoder"],
                                     jnp.asarray(x)[None])

    def _decode_fn(self, n_points: int, with_box: bool):
        key = (n_points, with_box)
        if key in self._decode_cache:
            return self._decode_cache[key]
        sam = self.sam
        s = sam.image_size

        @jax.jit
        def run(params, features, points, labels, boxes):
            pe = sam.prompt_encoder
            emb_hw = features.shape[1:3]
            sparse_parts = []
            if n_points:
                sparse_parts.append(
                    pe.apply({"params": params["prompt_encoder"]},
                             points, labels, (s, s),
                             method=PromptEncoder.embed_points)
                )
            if with_box:
                sparse_parts.append(
                    pe.apply({"params": params["prompt_encoder"]},
                             boxes, (s, s),
                             method=PromptEncoder.embed_boxes)
                )
            sparse = jnp.concatenate(sparse_parts, axis=1)
            n = sparse.shape[0]
            dense = pe.apply({"params": params["prompt_encoder"]},
                             n, emb_hw, method=PromptEncoder.no_mask_dense)
            image_pe = pe.apply({"params": params["prompt_encoder"]},
                                emb_hw, method=PromptEncoder.dense_pe)
            masks, iou = sam.mask_decoder.apply(
                {"params": params["mask_decoder"]},
                features.astype(jnp.float32), image_pe, sparse, dense,
            )
            # lowres (N, 4h, 4w) logits -> full padded-square resolution
            return resize_align_corners(masks, (s, s)), iou

        self._decode_cache[key] = run
        return run

    def predict(
        self,
        point_coords: Optional[np.ndarray] = None,
        point_labels: Optional[np.ndarray] = None,
        box: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Prompts in ORIGINAL image pixel coords. point_coords (K, 2),
        point_labels (K,) in {0, 1}; box (4,) xyxy. Returns
        (mask (H, W) bool in original resolution, iou_pred ()).
        """
        if self.features is None:
            raise RuntimeError("call set_image() first")
        n_points = 0 if point_coords is None else len(point_coords)
        with_box = box is not None
        if not n_points and not with_box:
            raise ValueError("give points and/or a box")

        pts = (np.zeros((1, 1, 2), np.float32) if not n_points else
               np.asarray(point_coords, np.float32)[None] * self.scale)
        labs = (np.zeros((1, 1), np.int32) if not n_points else
                np.asarray(point_labels, np.int32)[None])
        bx = (np.zeros((1, 4), np.float32) if not with_box else
              np.asarray(box, np.float32)[None] * self.scale)

        run = self._decode_fn(n_points, with_box)
        masks, iou = run(self.sam.params, self.features, jnp.asarray(pts),
                         jnp.asarray(labs), jnp.asarray(bx))
        mask = self._to_original(np.asarray(masks[0]))
        return mask, float(np.asarray(iou)[0])

    def _to_original(self, mask_logits: np.ndarray) -> np.ndarray:
        """Padded-square logits -> original-resolution bool mask
        (predictor.py postprocessing: unpad then resize)."""
        import cv2

        h, w = self.orig_hw
        # same half-up rounding as sam_longest_side_preprocess — int(round())
        # banker's-rounds and crops one pixel short when h*scale lands on .5
        sh, sw = int(h * self.scale + 0.5), int(w * self.scale + 0.5)
        crop = mask_logits[:sh, :sw]
        full = cv2.resize(crop, (w, h), interpolation=cv2.INTER_LINEAR)
        return full > 0


class SamAutomaticMaskGenerator:
    """Grid-prompted whole-image mask proposals
    (automatic_mask_generator.py:33-372): per crop-pyramid layer, a point
    grid -> batched single-point decodes -> IoU-prediction + stability +
    crop-edge filtering -> within-crop NMS -> uncrop -> cross-crop NMS
    (smaller crops preferred) -> optional small-region cleanup -> RLE/binary
    output. Mask bookkeeping lives in tmr_tpu.sam_amg."""

    def __init__(
        self,
        sam: Sam,
        points_per_side: Optional[int] = 32,
        points_per_batch: int = 64,
        pred_iou_thresh: float = 0.88,
        stability_score_thresh: float = 0.95,
        stability_score_offset: float = 1.0,
        box_nms_thresh: float = 0.7,
        crop_n_layers: int = 0,
        crop_nms_thresh: float = 0.7,
        crop_overlap_ratio: float = 512 / 1500,
        crop_n_points_downscale_factor: int = 1,
        point_grids: Optional[list] = None,
        min_mask_region_area: int = 0,
        output_mode: str = "binary_mask",
    ):
        from tmr_tpu.sam_amg import build_all_layer_point_grids

        if (points_per_side is None) == (point_grids is None):
            raise ValueError(
                "exactly one of points_per_side / point_grids must be set"
            )
        if points_per_side is not None:
            self.point_grids = build_all_layer_point_grids(
                points_per_side, crop_n_layers, crop_n_points_downscale_factor
            )
        else:
            self.point_grids = point_grids
        if output_mode not in ("binary_mask", "uncompressed_rle", "coco_rle"):
            raise ValueError(f"unknown output_mode {output_mode!r}")
        if output_mode == "coco_rle":
            # fail at construction like the reference
            # (automatic_mask_generator.py:119-121)
            from pycocotools import mask as _  # noqa: F401

        self.predictor = SamPredictor(sam)
        self.points_per_batch = points_per_batch
        self.pred_iou_thresh = pred_iou_thresh
        self.stability_score_thresh = stability_score_thresh
        self.stability_score_offset = stability_score_offset
        self.box_nms_thresh = box_nms_thresh
        self.crop_n_layers = crop_n_layers
        self.crop_nms_thresh = crop_nms_thresh
        self.crop_overlap_ratio = crop_overlap_ratio
        self.min_mask_region_area = min_mask_region_area
        self.output_mode = output_mode
        self._chunk_fn = None

    def _decode_points_chunk(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        sam = self.predictor.sam
        s = sam.image_size
        off = self.stability_score_offset

        @jax.jit
        def run(params, features, points):
            """points (C, 2) px in model space -> per-point mask stats."""
            pe = sam.prompt_encoder
            emb_hw = features.shape[1:3]
            labels = jnp.ones(points.shape[:1] + (1,), jnp.int32)
            sparse = pe.apply({"params": params["prompt_encoder"]},
                              points[:, None, :], labels, (s, s),
                              method=PromptEncoder.embed_points)
            dense = pe.apply({"params": params["prompt_encoder"]},
                             sparse.shape[0], emb_hw,
                             method=PromptEncoder.no_mask_dense)
            image_pe = pe.apply({"params": params["prompt_encoder"]},
                                emb_hw, method=PromptEncoder.dense_pe)
            masks, iou = sam.mask_decoder.apply(
                {"params": params["mask_decoder"]},
                features.astype(jnp.float32), image_pe, sparse, dense,
            )  # (C, 4h, 4w) logits
            binary = masks > 0
            area = binary.sum(axis=(1, 2))
            # stability = IoU between masks thresholded at +/- offset
            hi = (masks > off).sum(axis=(1, 2))
            lo = (masks > -off).sum(axis=(1, 2))
            stability = hi / jnp.maximum(lo, 1)
            from tmr_tpu.models.sam_decoder import masks_to_boxes

            boxes, nonempty = masks_to_boxes(binary)
            return masks, iou, stability, area, boxes, nonempty

        self._chunk_fn = run
        return run

    def _nms_keep(self, boxes: np.ndarray, scores: np.ndarray,
                  thresh: float, scale: float) -> np.ndarray:
        from tmr_tpu.ops.nms import nms_keep_mask

        return np.asarray(
            nms_keep_mask(
                jnp.asarray(boxes / scale, jnp.float32),
                jnp.asarray(scores, jnp.float32), thresh,
            )
        )

    def _process_crop(self, image: np.ndarray, crop_box: list,
                      layer_idx: int, orig_size: tuple) -> dict:
        """One crop: embed -> point-grid decodes -> quality + crop-edge
        filters -> within-crop NMS -> uncrop to the image frame
        (automatic_mask_generator.py:228-271)."""
        from tmr_tpu import sam_amg

        orig_h, orig_w = orig_size
        cx0, cy0, cx1, cy1 = crop_box
        cropped = image[cy0:cy1, cx0:cx1]
        ch, cw = cropped.shape[:2]
        pred = self.predictor
        pred.set_image(cropped)
        s = pred.sam.image_size

        grid_crop = self.point_grids[layer_idx] * np.array([[cw, ch]])
        run = self._decode_points_chunk()
        chunk = self.points_per_batch
        n_pts = len(grid_crop)
        n_pad = math.ceil(n_pts / chunk) * chunk
        grid_model = np.pad(
            grid_crop * pred.scale, ((0, n_pad - n_pts), (0, 0))
        )

        masks_crop, boxes_crop, ious, stabs, points = [], [], [], [], []
        for i in range(0, n_pad, chunk):
            pts = jnp.asarray(grid_model[i : i + chunk], jnp.float32)
            mask_logits, iou, stab, _, _, nonempty = run(
                pred.sam.params, pred.features, pts
            )
            iou = np.asarray(iou)
            stab = np.asarray(stab)
            # reference thresholds: iou strictly >, stability >=
            # (automatic_mask_generator.py _process_batch)
            keep = (
                (iou > self.pred_iou_thresh)
                & (stab >= self.stability_score_thresh)
                & np.asarray(nonempty)
            )
            keep[max(0, n_pts - i):] = False  # padding points
            kept = np.nonzero(keep)[0]
            if len(kept) == 0:
                continue
            # low-res logits -> padded-square resolution, then unpad-crop
            full = np.asarray(
                resize_align_corners(mask_logits[kept], (s, s))
            )
            for row, j in enumerate(kept):
                mask = pred._to_original(full[row])  # (ch, cw) bool
                ys_, xs_ = np.nonzero(mask)
                if len(xs_) == 0:
                    continue
                masks_crop.append(mask)
                boxes_crop.append(
                    [xs_.min(), ys_.min(), xs_.max(), ys_.max()]
                )
                ious.append(float(iou[j]))
                stabs.append(float(stab[j]))
                points.append(grid_crop[i + j])

        if not masks_crop:
            return {}
        boxes_crop = np.asarray(boxes_crop, np.float32)
        ious = np.asarray(ious, np.float32)

        # drop masks cut by the crop edge (amg.py:78-89) BEFORE deduping, like the
        # reference (_process_batch filters, then _process_crop NMSes) — an
        # edge-cut mask must never suppress a valid interior mask
        edge = sam_amg.is_box_near_crop_edge(
            boxes_crop, crop_box, [0, 0, orig_w, orig_h]
        )
        keep = ~edge
        if keep.any():
            from tmr_tpu.ops.nms import nms_keep_mask

            nms_keep = np.asarray(
                nms_keep_mask(
                    jnp.asarray(boxes_crop / max(ch, cw), jnp.float32),
                    jnp.asarray(ious, jnp.float32),
                    self.box_nms_thresh,
                    valid=jnp.asarray(keep),
                )
            )
            keep &= nms_keep
        idx = np.nonzero(keep)[0]
        if len(idx) == 0:
            return {}

        rles = [
            sam_amg.mask_to_rle(
                sam_amg.uncrop_mask(masks_crop[i], crop_box, orig_h, orig_w)
            )
            for i in idx
        ]
        return {
            "rles": rles,
            "boxes": sam_amg.uncrop_boxes_xyxy(boxes_crop[idx], crop_box),
            "iou_preds": ious[idx],
            "stability": np.asarray(stabs, np.float32)[idx],
            "points": sam_amg.uncrop_points(
                np.asarray(points, np.float32)[idx], crop_box
            ),
            "crop_boxes": np.tile(
                np.asarray(crop_box, np.float32)[None], (len(idx), 1)
            ),
        }

    def _postprocess_small_regions(self, data: dict, min_area: int,
                                   nms_thresh: float, orig_size: tuple) -> dict:
        """Fill small holes / drop small islands, then re-dedupe preferring
        untouched masks (automatic_mask_generator.py:283-332)."""
        from tmr_tpu import sam_amg

        new_rles, new_boxes, unchanged = [], [], []
        for rle in data["rles"]:
            mask = sam_amg.rle_to_mask(rle)
            mask, ch_holes = sam_amg.remove_small_regions(
                mask, min_area, "holes"
            )
            mask, ch_isl = sam_amg.remove_small_regions(
                mask, min_area, "islands"
            )
            new_rles.append(sam_amg.mask_to_rle(mask))
            ys_, xs_ = np.nonzero(mask)
            if len(xs_) == 0:
                new_boxes.append([0.0, 0.0, 0.0, 0.0])
            else:
                new_boxes.append(
                    [xs_.min(), ys_.min(), xs_.max(), ys_.max()]
                )
            unchanged.append(not (ch_holes or ch_isl))
        new_boxes = np.asarray(new_boxes, np.float32)
        # prefer masks NMS didn't have to touch
        keep = self._nms_keep(
            new_boxes, np.asarray(unchanged, np.float32), nms_thresh,
            max(orig_size),
        )
        data = dict(data)
        data["rles"] = new_rles
        data["boxes"] = new_boxes
        return sam_amg.filter_records(data, keep)

    def generate(self, image: np.ndarray) -> list:
        """image (H, W, 3) uint8 -> list of {segmentation, area, bbox
        (XYWH px), predicted_iou, stability_score, point_coords, crop_box}
        dicts, NMS-deduped, sorted by predicted IoU
        (automatic_mask_generator.py:122-226)."""
        from tmr_tpu import sam_amg

        orig_h, orig_w = image.shape[:2]
        crop_boxes, layer_idxs = sam_amg.generate_crop_boxes(
            (orig_h, orig_w), self.crop_n_layers, self.crop_overlap_ratio
        )
        data = sam_amg.cat_records(
            *[
                self._process_crop(image, cb, li, (orig_h, orig_w))
                for cb, li in zip(crop_boxes, layer_idxs)
            ]
        )
        if not data or len(data["rles"]) == 0:
            return []

        if len(crop_boxes) > 1:
            # dedupe across crops, preferring masks from smaller crops
            areas = (data["crop_boxes"][:, 2] - data["crop_boxes"][:, 0]) * (
                data["crop_boxes"][:, 3] - data["crop_boxes"][:, 1]
            )
            keep = self._nms_keep(
                data["boxes"], 1.0 / np.maximum(areas, 1.0),
                self.crop_nms_thresh, max(orig_h, orig_w),
            )
            data = sam_amg.filter_records(data, keep)

        if self.min_mask_region_area > 0:
            data = self._postprocess_small_regions(
                data, self.min_mask_region_area,
                max(self.box_nms_thresh, self.crop_nms_thresh),
                (orig_h, orig_w),
            )

        out = []
        for i, rle in enumerate(data["rles"]):
            if self.output_mode == "coco_rle":
                seg = sam_amg.coco_encode_rle(rle)
            elif self.output_mode == "binary_mask":
                seg = sam_amg.rle_to_mask(rle)
            else:
                seg = rle
            area = sam_amg.area_from_rle(rle)
            if area == 0:
                continue
            out.append(
                {
                    "segmentation": seg,
                    "area": area,
                    # XYWH with w = x_max - x_min (inclusive-max XYXY through
                    # box_xyxy_to_xywh — the reference's batched_mask_to_box
                    # + box_xyxy_to_xywh convention)
                    "bbox": sam_amg.box_xyxy_to_xywh(
                        data["boxes"][i]
                    ).tolist(),
                    "predicted_iou": float(data["iou_preds"][i]),
                    "stability_score": float(data["stability"][i]),
                    "point_coords": [np.asarray(data["points"][i]).tolist()],
                    "crop_box": sam_amg.box_xyxy_to_xywh(
                        np.asarray(data["crop_boxes"][i])
                    ).tolist(),
                }
            )
        out.sort(key=lambda d: -d["predicted_iou"])
        return out


class SamDeployDecoder:
    """Deployable prompt->mask program (utils/segment_anything/utils/onnx.py
    ``SamOnnxModel``): prompt encoding + mask decoding + mask postprocessing
    in one traceable function with the same input surface, so a runtime with
    no model code can drive SAM from cached image embeddings.

    Where the reference exports to ONNX with dynamic shapes, the TPU-native
    artifact is serialized StableHLO (utils/export.export_sam_decoder) with
    a symbolic prompt-count dimension; ``orig_im_size`` is a static build
    argument (XLA compiles per output resolution — resolutions are few and
    the compile is cached, vs. ONNX carrying dynamic resize ops).
    """

    def __init__(
        self,
        sam: Sam,
        return_single_mask: bool,
        use_stability_score: bool = False,
        return_extra_metrics: bool = False,
        stability_score_offset: float = 1.0,
        mask_threshold: float = 0.0,
    ):
        self.sam = sam
        self.decoder_all = sam.mask_decoder.clone(return_all_masks=True)
        self.return_single_mask = return_single_mask
        self.use_stability_score = use_stability_score
        self.return_extra_metrics = return_extra_metrics
        self.stability_score_offset = stability_score_offset
        self.mask_threshold = mask_threshold

    @staticmethod
    def resize_longest_image_size(orig_hw, longest_side: int):
        """floor(scale * size + 0.5) (onnx.py:41-48)."""
        h, w = orig_hw
        scale = longest_side / max(h, w)
        return (int(scale * h + 0.5), int(scale * w + 0.5))

    def _select_masks(self, masks, iou_preds, num_points):
        """Single-click vs multi-click token choice without control flow
        (onnx.py:95-108): with <= 2 point slots (one click + padding) token 0
        is penalized by -500 so the best multimask token (1..3) wins; with
        > 2 real clicks token 0 is boosted by +500 and always wins."""
        t = masks.shape[1]
        reweight = jnp.asarray([1000.0] + [0.0] * (t - 1))
        score = iou_preds + (num_points - 2.5) * reweight[None]
        best = jnp.argmax(score, axis=1)
        m = jnp.take_along_axis(masks, best[:, None, None, None], axis=1)
        s = jnp.take_along_axis(iou_preds, best[:, None], axis=1)
        return m, s  # (N, 1, H, W), (N, 1)

    def _stability(self, masks):
        off = self.stability_score_offset
        hi = (masks > self.mask_threshold + off).sum((-1, -2))
        lo = (masks > self.mask_threshold - off).sum((-1, -2))
        return hi / jnp.maximum(lo, 1)

    def __call__(
        self,
        params: dict,
        image_embeddings: jnp.ndarray,  # (1, h, w, C)
        point_coords: jnp.ndarray,  # (N, P, 2) px in model space
        point_labels: jnp.ndarray,  # (N, P) in {-1, 0, 1}
        mask_input: jnp.ndarray,  # (N, 4h, 4w, 1)
        has_mask_input: jnp.ndarray,  # (N,) or scalar in {0., 1.}
        orig_im_size,  # static (H, W)
    ):
        """Mirrors SamOnnxModel.forward (onnx.py:110-144). Jittable."""
        sam = self.sam
        s = sam.image_size
        pe_params = {"params": params["prompt_encoder"]}
        emb_hw = image_embeddings.shape[1:3]
        pe = sam.prompt_encoder

        sparse = pe.apply(pe_params, point_coords, point_labels, (s, s),
                          method=PromptEncoder.embed_points)
        n = point_coords.shape[0]
        masked = pe.apply(pe_params, mask_input, method=PromptEncoder.embed_masks)
        unmasked = pe.apply(pe_params, n, emb_hw,
                            method=PromptEncoder.no_mask_dense)
        has = jnp.reshape(
            jnp.broadcast_to(jnp.asarray(has_mask_input, jnp.float32), (n,)),
            (n, 1, 1, 1),
        )
        dense = has * masked + (1.0 - has) * unmasked
        image_pe = pe.apply(pe_params, emb_hw, method=PromptEncoder.dense_pe)

        masks, scores = self.decoder_all.apply(
            {"params": params["mask_decoder"]},
            image_embeddings.astype(jnp.float32), image_pe, sparse, dense,
        )  # (N, T, 4h, 4w), (N, T)

        if self.use_stability_score:
            scores = self._stability(masks)
        if self.return_single_mask:
            masks, scores = self._select_masks(
                masks, scores, point_coords.shape[1]
            )

        # postprocess: 4h grid -> model square -> unpad -> original size
        # (onnx.py:77-93; align_corners=False at both resizes)
        up = jax.image.resize(
            masks, masks.shape[:2] + (s, s), method="bilinear",
            antialias=False,
        )
        ph, pw = self.resize_longest_image_size(orig_im_size, s)
        up = up[..., :ph, :pw]
        out = jax.image.resize(
            up, up.shape[:2] + tuple(orig_im_size), method="bilinear",
            antialias=False,
        )

        if self.return_extra_metrics:
            stab = self._stability(out)
            areas = (out > self.mask_threshold).sum((-1, -2))
            return out, scores, stab, areas, masks
        return out, scores, masks
