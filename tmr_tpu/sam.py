"""Segment-anything surface: model registry, predictor, auto mask generator.

The reference vendors Meta's SAM package (utils/segment_anything/ — SURVEY
§2.1 #18: ``sam_model_registry``/``build_sam.py:47-52``, ``SamPredictor``
(predictor.py), ``SamAutomaticMaskGenerator`` (automatic_mask_generator.py),
with two local patches: the mask decoder auto-picks the best-IoU mask
(mask_decoder.py:100-103) and upsamples mismatched PEs). This module is the
TPU-native equivalent built from the framework's own components: the Flax
SamViT encoder (models/vit.py), PromptEncoder/MaskDecoder
(models/sam_decoder.py — best-IoU selection built in, matching the
reference's patch), SAM preprocessing (data/transforms.py), and the
fixed-capacity NMS ops.

Design differences from the vendored package, deliberately TPU-first:
- encode/decode are jitted programs cached per prompt-batch bucket — the
  predictor encodes an image once and serves any number of prompt queries
  from the cached embedding (predictor.py's set_image/predict contract);
- the automatic mask generator runs the point grid as *batched* prompt
  chunks through one decode program (no per-point Python loop) and dedupes
  with the framework's padded NMS instead of torchvision's.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.data.transforms import sam_longest_side_preprocess
from tmr_tpu.models.sam_decoder import (
    MaskDecoder,
    PromptEncoder,
    resize_align_corners,
)
from tmr_tpu.models.vit import build_sam_vit


class Sam:
    """Encoder + prompt encoder + mask decoder with one params tree."""

    def __init__(self, model_type: str = "vit_b", params: Optional[dict] = None,
                 image_size: int = 1024):
        self.model_type = model_type
        self.image_size = image_size
        self.image_encoder = build_sam_vit(model_type, dtype=jnp.bfloat16)
        self.prompt_encoder = PromptEncoder()
        self.mask_decoder = MaskDecoder()
        self.params = params

    def init_random(self, seed: int = 0) -> dict:
        """Random init (smoke/tests; the reference builds weightless too)."""
        import flax.linen as nn

        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        s = self.image_size
        enc = jax.jit(self.image_encoder.init)(
            k1, jnp.zeros((1, s, s, 3), jnp.float32)
        )["params"]

        def init_pe(module):
            module(jnp.zeros((1, 4)), (s, s), (4, 4))
            module.embed_points(
                jnp.zeros((1, 2, 2)), jnp.zeros((1, 2), jnp.int32), (s, s)
            )
            module.embed_masks(jnp.zeros((1, 16, 16, 1)))

        pe = nn.init(init_pe, self.prompt_encoder)(k2)["params"]
        d = self.mask_decoder.transformer_dim
        md = self.mask_decoder.init(
            k3, jnp.zeros((1, 4, 4, d)), jnp.zeros((4, 4, d)),
            jnp.zeros((1, 2, d)), jnp.zeros((1, 4, 4, d)),
        )["params"]
        self.params = {"image_encoder": enc, "prompt_encoder": pe,
                       "mask_decoder": md}
        return self.params

    @classmethod
    def from_checkpoint(cls, path: str, model_type: str = "vit_b") -> "Sam":
        """Build from a full SAM/SAM-HQ .pth (build_sam.py registry role)."""
        from tmr_tpu.utils.convert import (
            convert_mask_decoder,
            convert_prompt_encoder,
            convert_sam_vit,
            load_torch_state_dict,
        )

        sd = load_torch_state_dict(path)
        params = {
            "image_encoder": convert_sam_vit(sd, "image_encoder."),
            "prompt_encoder": convert_prompt_encoder(sd),
            "mask_decoder": convert_mask_decoder(sd),
        }
        return cls(model_type, params=params)


# build_sam.py:47-52 registry equivalent
sam_model_registry: Dict[str, object] = {
    "vit_b": partial(Sam, "vit_b"),
    "vit_h": partial(Sam, "vit_h"),
    "default": partial(Sam, "vit_h"),
}


class SamPredictor:
    """Encode an image once; answer point/box prompt queries from the cached
    embedding (predictor.py:26-269 contract). Returns the best-IoU mask per
    prompt — the reference's patched decoder behavior."""

    def __init__(self, sam: Sam):
        self.sam = sam
        if sam.params is None:
            raise ValueError("Sam has no params; call init_random() or "
                             "from_checkpoint() first")
        self._encode = jax.jit(
            lambda p, x: sam.image_encoder.apply({"params": p}, x)
        )
        self._decode_cache: dict = {}
        self.reset_image()

    def reset_image(self):
        self.features = None
        self.orig_hw: Optional[Tuple[int, int]] = None
        self.scale: float = 1.0

    def set_image(self, image: np.ndarray) -> None:
        """image: (H, W, 3) uint8 RGB. Preprocess (resize longest side to
        1024, SAM normalize, pad) + one jitted encoder pass."""
        image = np.asarray(image)
        self.orig_hw = image.shape[:2]
        self.scale = self.sam.image_size / max(self.orig_hw)
        x = sam_longest_side_preprocess(image, self.sam.image_size)
        self.features = self._encode(self.sam.params["image_encoder"],
                                     jnp.asarray(x)[None])

    def _decode_fn(self, n_points: int, with_box: bool):
        key = (n_points, with_box)
        if key in self._decode_cache:
            return self._decode_cache[key]
        sam = self.sam
        s = sam.image_size

        @jax.jit
        def run(params, features, points, labels, boxes):
            pe = sam.prompt_encoder
            emb_hw = features.shape[1:3]
            sparse_parts = []
            if n_points:
                sparse_parts.append(
                    pe.apply({"params": params["prompt_encoder"]},
                             points, labels, (s, s),
                             method=PromptEncoder.embed_points)
                )
            if with_box:
                sparse_parts.append(
                    pe.apply({"params": params["prompt_encoder"]},
                             boxes, (s, s),
                             method=PromptEncoder.embed_boxes)
                )
            sparse = jnp.concatenate(sparse_parts, axis=1)
            n = sparse.shape[0]
            dense = pe.apply({"params": params["prompt_encoder"]},
                             n, emb_hw, method=PromptEncoder.no_mask_dense)
            image_pe = pe.apply({"params": params["prompt_encoder"]},
                                emb_hw, method=PromptEncoder.dense_pe)
            masks, iou = sam.mask_decoder.apply(
                {"params": params["mask_decoder"]},
                features.astype(jnp.float32), image_pe, sparse, dense,
            )
            # lowres (N, 4h, 4w) logits -> full padded-square resolution
            return resize_align_corners(masks, (s, s)), iou

        self._decode_cache[key] = run
        return run

    def predict(
        self,
        point_coords: Optional[np.ndarray] = None,
        point_labels: Optional[np.ndarray] = None,
        box: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Prompts in ORIGINAL image pixel coords. point_coords (K, 2),
        point_labels (K,) in {0, 1}; box (4,) xyxy. Returns
        (mask (H, W) bool in original resolution, iou_pred ()).
        """
        if self.features is None:
            raise RuntimeError("call set_image() first")
        n_points = 0 if point_coords is None else len(point_coords)
        with_box = box is not None
        if not n_points and not with_box:
            raise ValueError("give points and/or a box")

        pts = (np.zeros((1, 1, 2), np.float32) if not n_points else
               np.asarray(point_coords, np.float32)[None] * self.scale)
        labs = (np.zeros((1, 1), np.int32) if not n_points else
                np.asarray(point_labels, np.int32)[None])
        bx = (np.zeros((1, 4), np.float32) if not with_box else
              np.asarray(box, np.float32)[None] * self.scale)

        run = self._decode_fn(n_points, with_box)
        masks, iou = run(self.sam.params, self.features, jnp.asarray(pts),
                         jnp.asarray(labs), jnp.asarray(bx))
        mask = self._to_original(np.asarray(masks[0]))
        return mask, float(np.asarray(iou)[0])

    def _to_original(self, mask_logits: np.ndarray) -> np.ndarray:
        """Padded-square logits -> original-resolution bool mask
        (predictor.py postprocessing: unpad then resize)."""
        import cv2

        h, w = self.orig_hw
        # same half-up rounding as sam_longest_side_preprocess — int(round())
        # banker's-rounds and crops one pixel short when h*scale lands on .5
        sh, sw = int(h * self.scale + 0.5), int(w * self.scale + 0.5)
        crop = mask_logits[:sh, :sw]
        full = cv2.resize(crop, (w, h), interpolation=cv2.INTER_LINEAR)
        return full > 0


class SamAutomaticMaskGenerator:
    """Grid-prompted whole-image mask proposals
    (automatic_mask_generator.py:33-372, single-crop configuration):
    points_per_side grid -> batched single-point decodes -> IoU-prediction +
    stability filtering -> mask boxes -> padded-NMS dedupe."""

    def __init__(
        self,
        sam: Sam,
        points_per_side: int = 16,
        points_per_batch: int = 64,
        pred_iou_thresh: float = 0.88,
        stability_score_thresh: float = 0.95,
        stability_score_offset: float = 1.0,
        box_nms_thresh: float = 0.7,
    ):
        self.predictor = SamPredictor(sam)
        self.points_per_side = points_per_side
        self.points_per_batch = points_per_batch
        self.pred_iou_thresh = pred_iou_thresh
        self.stability_score_thresh = stability_score_thresh
        self.stability_score_offset = stability_score_offset
        self.box_nms_thresh = box_nms_thresh
        self._chunk_fn = None

    def _decode_points_chunk(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        sam = self.predictor.sam
        s = sam.image_size
        off = self.stability_score_offset

        @jax.jit
        def run(params, features, points):
            """points (C, 2) px in model space -> per-point mask stats."""
            pe = sam.prompt_encoder
            emb_hw = features.shape[1:3]
            labels = jnp.ones(points.shape[:1] + (1,), jnp.int32)
            sparse = pe.apply({"params": params["prompt_encoder"]},
                              points[:, None, :], labels, (s, s),
                              method=PromptEncoder.embed_points)
            dense = pe.apply({"params": params["prompt_encoder"]},
                             sparse.shape[0], emb_hw,
                             method=PromptEncoder.no_mask_dense)
            image_pe = pe.apply({"params": params["prompt_encoder"]},
                                emb_hw, method=PromptEncoder.dense_pe)
            masks, iou = sam.mask_decoder.apply(
                {"params": params["mask_decoder"]},
                features.astype(jnp.float32), image_pe, sparse, dense,
            )  # (C, 4h, 4w) logits
            binary = masks > 0
            area = binary.sum(axis=(1, 2))
            # stability = IoU between masks thresholded at +/- offset
            hi = (masks > off).sum(axis=(1, 2))
            lo = (masks > -off).sum(axis=(1, 2))
            stability = hi / jnp.maximum(lo, 1)
            from tmr_tpu.models.sam_decoder import masks_to_boxes

            boxes, nonempty = masks_to_boxes(binary)
            return masks, iou, stability, area, boxes, nonempty

        self._chunk_fn = run
        return run

    def generate(self, image: np.ndarray) -> list:
        """image (H, W, 3) uint8 -> list of {segmentation, area, bbox
        (XYWH px), predicted_iou, stability_score, point_coords} dicts,
        NMS-deduped, sorted by predicted IoU."""
        pred = self.predictor
        pred.set_image(image)
        s = pred.sam.image_size
        h, w = pred.orig_hw
        sh, sw = h * pred.scale, w * pred.scale

        n = self.points_per_side
        xs = (np.arange(n) + 0.5) / n * sw
        ys = (np.arange(n) + 0.5) / n * sh
        grid = np.stack(np.meshgrid(xs, ys), axis=-1).reshape(-1, 2)

        run = self._decode_points_chunk()
        chunk = self.points_per_batch
        n_pad = math.ceil(len(grid) / chunk) * chunk
        grid_p = np.pad(grid, ((0, n_pad - len(grid)), (0, 0)))

        results = []
        for i in range(0, n_pad, chunk):
            pts = jnp.asarray(grid_p[i : i + chunk], jnp.float32)
            masks, iou, stab, area, boxes, nonempty = run(
                pred.sam.params, pred.features, pts
            )
            iou = np.asarray(iou)
            stab = np.asarray(stab)
            keep = (
                (iou > self.pred_iou_thresh)
                & (stab > self.stability_score_thresh)
                & np.asarray(nonempty)
            )
            keep[max(0, len(grid) - i):] = False  # padding points
            for j in np.nonzero(keep)[0]:
                results.append(
                    {
                        "mask_logits": np.asarray(masks[j]),
                        "predicted_iou": float(iou[j]),
                        "stability_score": float(stab[j]),
                        "box_model": np.asarray(boxes[j]) * (s / masks.shape[1]),
                        "point_coords": grid_p[i + j] / pred.scale,
                    }
                )

        if not results:
            return []

        # NMS dedupe on mask boxes (automatic_mask_generator.py box_nms)
        from tmr_tpu.ops.nms import nms_keep_mask

        bx = jnp.asarray(
            np.stack([r["box_model"] for r in results]), jnp.float32
        )
        sc = jnp.asarray([r["predicted_iou"] for r in results], jnp.float32)
        keep = np.asarray(nms_keep_mask(bx / s, sc, self.box_nms_thresh))

        out = []
        for r, k in zip(results, keep):
            if not k:
                continue
            # low-res decoder logits -> full padded-square resolution first;
            # _to_original's unpad-crop works in model-space pixels
            full = np.asarray(
                resize_align_corners(
                    jnp.asarray(r["mask_logits"])[None], (s, s)
                )[0]
            )
            mask = pred._to_original(full)
            ys_, xs_ = np.nonzero(mask)
            if len(xs_) == 0:
                continue
            x0, y0 = int(xs_.min()), int(ys_.min())
            bw, bh = int(xs_.max() - x0 + 1), int(ys_.max() - y0 + 1)
            out.append(
                {
                    "segmentation": mask,
                    "area": int(mask.sum()),
                    "bbox": [x0, y0, bw, bh],
                    "predicted_iou": r["predicted_iou"],
                    "stability_score": r["stability_score"],
                    "point_coords": [r["point_coords"].tolist()],
                }
            )
        out.sort(key=lambda d: -d["predicted_iou"])
        return out
