"""SAM-based box refinement — TPU-native rebuild of utils/box_refine.py.

The reference refiner (box_refine.py:22-258) takes the detector's predicted
boxes, feeds them in chunks of 50 as prompts to a SAM mask decoder over the
frozen encoder features, converts each predicted mask to its tight bbox, and
rescores detections as ``iou_pred * original_score`` (the "type 2" scoring of
box_refine.py:253).

TPU redesign:
- The PromptEncoder/MaskDecoder are built ONCE; image and feature-grid sizes
  are call inputs (the reference re-instantiates and re-loads the prompt
  encoder per image, box_refine.py:207).
- Detections arrive as fixed-capacity padded slot arrays (the output of
  ops/postprocess.batched_nms), so the whole refinement is a single jittable
  program: prompts are processed in static chunks via ``lax.map`` (bounding
  peak memory like the reference's step=50), masks are upsampled with the
  reference's align_corners=True bilinear, and the mask->bbox conversion is
  the in-XLA reduction of models/sam_decoder.masks_to_boxes instead of a
  python loop over torch.where (box_refine.py:236-242).
- Invalid (padding) slots pass through untouched; empty masks keep the
  original box, matching the reference's zeros-then-overwrite behavior.

The exemplar-scaled variant (box_refine.py:64-188 ``forward_refine``) is
``refine_with_exemplar_scaling``.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.models.sam_decoder import (
    MaskDecoder,
    PromptEncoder,
    masks_to_boxes,
    resize_align_corners,
)


class SamRefineModule:
    """Holds the (build-once) prompt encoder + mask decoder and their params."""

    def __init__(self, params: Optional[dict] = None, chunk: int = 50):
        self.prompt_encoder = PromptEncoder()
        self.mask_decoder = MaskDecoder()
        self.params = params
        self.chunk = chunk  # reference step=50 (box_refine.py:26)
        self._jitted = {}

    def init_params(self, seed: int = 0) -> dict:
        """Random init (tests / no-checkpoint runs)."""
        k1, k2 = jax.random.split(jax.random.key(seed))
        d = self.mask_decoder.transformer_dim

        def init_all_paths(module):
            # traverse every prompt path so point/mask params materialize too
            module(jnp.zeros((1, 4)), (64, 64), (4, 4))
            module.embed_points(
                jnp.zeros((1, 2, 2)), jnp.zeros((1, 2), jnp.int32), (64, 64)
            )
            module.embed_masks(jnp.zeros((1, 16, 16, 1)))

        pe = nn.init(init_all_paths, self.prompt_encoder)(k1)["params"]
        md = self.mask_decoder.init(
            k2,
            jnp.zeros((1, 4, 4, d)),
            jnp.zeros((4, 4, d)),
            jnp.zeros((1, 2, d)),
            jnp.zeros((1, 4, 4, d)),
        )["params"]
        self.params = {"prompt_encoder": pe, "mask_decoder": md}
        return self.params

    # ----- single-chunk core ------------------------------------------------

    def _decode_chunk(
        self,
        params: dict,
        features: jnp.ndarray,  # (1, h, w, 256)
        image_pe: jnp.ndarray,  # (h, w, 256)
        boxes_px: jnp.ndarray,  # (C, 4) xyxy pixels
        image_size: Tuple[int, int],
        mask_size: Tuple[int, int],
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One chunk of box prompts -> (boxes_px (C,4), iou (C,), nonempty (C,))."""
        sparse, dense = self.prompt_encoder.apply(
            {"params": params["prompt_encoder"]},
            boxes_px,
            image_size,
            features.shape[1:3],
        )
        masks, iou = self.mask_decoder.apply(
            {"params": params["mask_decoder"]},
            features,
            image_pe,
            sparse,
            dense,
        )
        # (C, 4h, 4w) logits -> align_corners bilinear to mask_size -> >0
        masks = resize_align_corners(masks, mask_size) > 0
        boxes, nonempty = masks_to_boxes(masks)
        sy = image_size[0] / mask_size[0]
        sx = image_size[1] / mask_size[1]
        scale = jnp.asarray([sx, sy, sx, sy], jnp.float32)
        return boxes * scale, iou, nonempty

    # ----- full refinement over padded detection slots ----------------------

    def refine(
        self,
        params: dict,
        features: jnp.ndarray,  # (B, h, w, 256) frozen encoder output
        dets: dict,  # boxes (B, N, 4) normalized xyxy; scores; valid
        image_size: Tuple[int, int],
        mask_size: Optional[Tuple[int, int]] = None,
    ) -> dict:
        """Jittable refinement of a padded detection set.

        Returns a dict with the same keys; every valid slot's score becomes
        ``iou_pred * original_score`` (box_refine.py:253) and its box the
        mask-tight box normalized to [0, 1]. Valid slots whose mask came out
        empty keep their original box (but are still rescored); invalid
        (padding) slots keep both box and score.
        """
        if mask_size is None:
            # the reference upsamples masks to the full image; a 4x-coarser
            # grid (the decoder's native output) changes boxes by <1px at
            # 1024 but costs 16x less HBM — keep full-res for parity.
            mask_size = image_size
        b, n, _ = dets["boxes"].shape
        h_img, w_img = image_size
        res = jnp.asarray([w_img, h_img, w_img, h_img], jnp.float32)

        chunk = min(self.chunk, n)
        n_pad = math.ceil(n / chunk) * chunk
        pad = n_pad - n

        def per_image(feat, boxes, scores, valid):
            image_pe = self.prompt_encoder.apply(
                {"params": params["prompt_encoder"]},
                feat.shape[0:2],
                method=PromptEncoder.dense_pe,
            )
            boxes_px = boxes * res
            boxes_px = jnp.pad(boxes_px, ((0, pad), (0, 0)))
            chunks = boxes_px.reshape(n_pad // chunk, chunk, 4)
            new_boxes, ious, nonempty = jax.lax.map(
                lambda bx: self._decode_chunk(
                    params, feat[None], image_pe, bx, image_size, mask_size
                ),
                chunks,
            )
            new_boxes = new_boxes.reshape(n_pad, 4)[:n] / res
            ious = ious.reshape(n_pad)[:n]
            nonempty = nonempty.reshape(n_pad)[:n]
            keep_new = valid & nonempty
            out_boxes = jnp.where(keep_new[:, None], new_boxes, boxes)
            out_scores = jnp.where(valid, ious * scores, scores)
            return out_boxes, out_scores

        out_boxes, out_scores = jax.vmap(per_image)(
            features, dets["boxes"], dets["scores"], dets["valid"]
        )
        refs = jnp.stack(
            [
                (out_boxes[..., 0] + out_boxes[..., 2]) / 2,
                (out_boxes[..., 1] + out_boxes[..., 3]) / 2,
            ],
            axis=-1,
        )
        out = dict(dets)
        out.update(boxes=out_boxes, scores=out_scores, refs=refs)
        return out

    def refine_with_exemplar_scaling(
        self,
        params: dict,
        features: jnp.ndarray,  # (B, h, w, 256)
        dets: dict,
        exemplars: jnp.ndarray,  # (B, 4) normalized xyxy (first exemplar)
        image_size: Tuple[int, int],
        mask_size: Optional[Tuple[int, int]] = None,
    ) -> dict:
        """The ``forward_refine`` variant (box_refine.py:64-188): compute a
        per-image ltrb scale factor from (exemplar box / exemplar's own SAM
        mask box) and apply it to every refined box."""
        if mask_size is None:
            mask_size = image_size
        h_img, w_img = image_size
        res = jnp.asarray([w_img, h_img, w_img, h_img], jnp.float32)

        def exemplar_scaler(feat, ex_box):
            image_pe = self.prompt_encoder.apply(
                {"params": params["prompt_encoder"]},
                feat.shape[0:2],
                method=PromptEncoder.dense_pe,
            )
            mask_box_px, _, nonempty = self._decode_chunk(
                params, feat[None], image_pe, (ex_box * res)[None],
                image_size, mask_size,
            )
            mb = mask_box_px[0] / res  # normalized xyxy of the exemplar mask
            cx, cy = (mb[0] + mb[2]) / 2, (mb[1] + mb[3]) / 2
            ltrb = jnp.stack([cx - mb[0], cy - mb[1], mb[2] - cx, mb[3] - cy])
            ex_ltrb = jnp.stack(
                [cx - ex_box[0], cy - ex_box[1], ex_box[2] - cx, ex_box[3] - cy]
            )
            scaler = ex_ltrb / jnp.maximum(ltrb, 1e-8)
            return jnp.where(nonempty[0], scaler, jnp.ones(4))

        scalers = jax.vmap(exemplar_scaler)(features, exemplars)  # (B, 4)
        refined = self.refine(params, features, dets, image_size, mask_size)

        boxes = refined["boxes"]
        cx = (boxes[..., 0] + boxes[..., 2]) / 2
        cy = (boxes[..., 1] + boxes[..., 3]) / 2
        ltrb = jnp.stack(
            [cx - boxes[..., 0], cy - boxes[..., 1],
             boxes[..., 2] - cx, boxes[..., 3] - cy],
            axis=-1,
        )
        ltrb = ltrb * scalers[:, None, :]
        boxes = jnp.stack(
            [cx - ltrb[..., 0], cy - ltrb[..., 1],
             cx + ltrb[..., 2], cy + ltrb[..., 3]],
            axis=-1,
        )
        refined["boxes"] = jnp.where(
            refined["valid"][..., None], boxes, refined["boxes"]
        )
        return refined

    def decode_masks(
        self,
        params: dict,
        features: jnp.ndarray,  # (B, h, w, 256)
        boxes: jnp.ndarray,  # (B, N, 4) normalized
        image_size: Tuple[int, int],
        valid: Optional[jnp.ndarray] = None,  # (B, N) bool
    ) -> jnp.ndarray:
        """Union mask per image (B, H, W) bool — the ``save_masks`` path
        (box_refine.py:260-307) minus the cv2 file write. Padding slots
        (``valid`` False) contribute nothing to the union."""
        h_img, w_img = image_size
        res = jnp.asarray([w_img, h_img, w_img, h_img], jnp.float32)
        if valid is None:
            valid = jnp.ones(boxes.shape[:2], bool)
        n = boxes.shape[1]
        if n == 0:  # zero detection slots -> empty union masks
            return jnp.zeros((boxes.shape[0],) + tuple(image_size), bool)
        chunk = min(self.chunk, n)
        n_pad = math.ceil(n / chunk) * chunk

        def per_image(feat, bxs, val):
            image_pe = self.prompt_encoder.apply(
                {"params": params["prompt_encoder"]},
                feat.shape[0:2],
                method=PromptEncoder.dense_pe,
            )
            bxs_p = jnp.pad(bxs * res, ((0, n_pad - n), (0, 0)))
            val_p = jnp.pad(val, (0, n_pad - n))

            def one_chunk(args):
                cb, cv = args
                sparse, dense = self.prompt_encoder.apply(
                    {"params": params["prompt_encoder"]},
                    cb,
                    image_size,
                    feat.shape[0:2],
                )
                masks, _ = self.mask_decoder.apply(
                    {"params": params["mask_decoder"]},
                    feat[None],
                    image_pe,
                    sparse,
                    dense,
                )
                masks = resize_align_corners(masks, image_size) > 0
                return jnp.any(masks & cv[:, None, None], axis=0)

            # bound HBM like refine(): self.chunk prompts per decode
            # (the reference steps by 50, box_refine.py:279)
            chunk_masks = jax.lax.map(
                one_chunk,
                (bxs_p.reshape(n_pad // chunk, chunk, 4),
                 val_p.reshape(n_pad // chunk, chunk)),
            )
            return jnp.any(chunk_masks, axis=0)

        return jax.vmap(per_image)(features, boxes, valid)

    def save_masks(
        self,
        params: dict,
        features: jnp.ndarray,
        dets: dict,
        image_size: Tuple[int, int],
        log_path: str,
        img_names,
    ) -> list:
        """Dump per-image union masks to {log_path}/masks/{img_name}.png
        (box_refine.py:260-307: 255 = covered by some predicted box mask)."""
        import os

        import cv2

        out_dir = os.path.join(log_path, "masks")
        os.makedirs(out_dir, exist_ok=True)
        masks = self.decode_masks(
            params, features, dets["boxes"], image_size,
            valid=dets.get("valid"),
        )
        written = []
        for mask, name in zip(np.asarray(masks), img_names):
            path = os.path.join(out_dir, f"{name}.png")
            cv2.imwrite(path, (mask * 255).astype(np.uint8))
            written.append(path)
        return written


def build_refiner(cfg, seed: int = 0):
    """Build-once refiner + params for --refine_box runs (the reference
    constructs its SAM refiner inside the test step, trainer.py:146-148,
    pulling weights from public URLs, box_refine.py:41-60).

    With ``cfg.refiner_checkpoint`` the SAM ``.pth`` converts to Flax params;
    without one (airgapped TPU pods cannot hit the reference's download
    URLs) the decoder initializes randomly with a loud warning — the
    pipeline shape/order is exercised either way.
    """
    refiner = SamRefineModule()
    ckpt = getattr(cfg, "refiner_checkpoint", None)
    if ckpt:
        from tmr_tpu.utils.convert import (
            convert_sam_refiner,
            load_torch_state_dict,
        )

        params = convert_sam_refiner(load_torch_state_dict(ckpt))
    else:
        from tmr_tpu.utils.profiling import log_warning

        log_warning(
            "refine_box: no refiner_checkpoint configured; using random-init "
            "SAM decoder weights (boxes will be refined by an untrained mask "
            "decoder)"
        )
        params = refiner.init_params(seed=seed)
    return refiner, params
