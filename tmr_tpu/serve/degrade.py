"""Adaptive degradation ladder: elect cheaper program variants under
overload instead of shedding blindly.

The FastFlow/CrossVLA lesson from PAPERS.md applied to this engine: when
the health watch says the engine is drowning (queue saturation, p99
regression, MFU drop — the PR 8 ``health_report/v1`` anomalies), the
controller climbs a ladder of degrade steps the engine already supports
numerically, and steps back down once the pressure clears:

- level 1 ``truncate_k``   — multi-exemplar requests run with k_real=1
  (the matcher's cost is ~linear in k; the union-NMS program at k=1 is
  the cheapest legal variant of the request).
- level 2 ``prefer_heads`` — images promote into the feature cache on
  FIRST sighting instead of the second, so repeat traffic lands on the
  cached heads-only program (encoder skipped) one round-trip earlier.
- level 3 ``downscale``    — the image routes to the half-resolution
  bucket (2x2 subsample host-side; exemplar boxes are normalized, so
  detections stay in the same coordinate space) — ~4x fewer
  backbone FLOPs per admitted request.

Exactness contract: a degrade step is NEVER silent. Every result served
with any step active carries ``degrade_steps`` listing exactly which
steps fired, and with the ladder disabled (``TMR_DEGRADE`` unset, the
default) requests trace the byte-identical PR 3 path — bitwise
exactness is relaxed only when a step explicitly fired and said so.

The controller is driven by ``ServeEngine.health()`` passes (the
heartbeat's interval IS the control interval): anomalies escalate one
level per pass, ``cooldown`` consecutive calm passes de-escalate one
level. ``TMR_DEGRADE`` accepts ``auto`` (anomaly-driven) or a forced
integer level (probes/tests pin the ladder deterministically).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence

#: the ladder, in escalation order — level N activates steps [:N]
DEGRADE_STEPS = ("truncate_k", "prefer_heads", "downscale")

#: health anomaly kinds that signal overload (the ladder's escalation
#: triggers); recompile_storm / cache_hit_collapse are efficiency bugs,
#: not load, and must not shrink user results
OVERLOAD_ANOMALY_KINDS = (
    "queue_saturation",
    "latency_regression",
    "mfu_drop",
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class DegradeController:
    """The degrade-ladder state machine.

    ``mode``: "off" (default; the controller is inert and the engine
    path is untouched), "auto" (anomaly-driven escalation), or an
    integer string — a forced, pinned level. Resolution order:
    constructor arg > ``TMR_DEGRADE`` env > off.
    """

    def __init__(self, mode: Optional[str] = None,
                 max_level: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 min_size: Optional[int] = None):
        mode = (os.environ.get("TMR_DEGRADE", "off") or "off") \
            if mode is None else str(mode)
        self.max_level = (
            max(min(_env_int("TMR_DEGRADE_MAX_LEVEL", len(DEGRADE_STEPS)),
                    len(DEGRADE_STEPS)), 1)
            if max_level is None
            else max(min(int(max_level), len(DEGRADE_STEPS)), 1)
        )
        self.cooldown = (
            max(_env_int("TMR_DEGRADE_COOLDOWN", 2), 1)
            if cooldown is None else max(int(cooldown), 1)
        )
        #: downscale floor: images at/below this size never downscale
        #: (the feature grid must stay meaningful)
        self.min_size = (
            max(_env_int("TMR_DEGRADE_MIN_SIZE", 128), 2)
            if min_size is None else max(int(min_size), 2)
        )
        self._lock = threading.Lock()
        self._calm = 0
        self._level = 0
        self._forced: Optional[int] = None
        if mode in ("off", "0", "", "false"):
            self.enabled = False
            self.mode = "off"
        elif mode == "auto":
            self.enabled = True
            self.mode = "auto"
        else:
            try:
                forced = int(mode)
            except ValueError:
                raise ValueError(
                    f"TMR_DEGRADE={mode!r}: expected off|auto|<level int>"
                )
            self.enabled = forced > 0
            self.mode = "forced"
            self._forced = max(min(forced, self.max_level), 0)
            self._level = self._forced

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def active_steps(self) -> Sequence[str]:
        """The steps the current level activates (escalation order)."""
        if not self.enabled:
            return ()
        with self._lock:
            return DEGRADE_STEPS[:self._level]

    def observe(self, anomalies: Sequence[dict]) -> int:
        """One control pass over a health snapshot's anomaly records:
        any overload-signaling anomaly escalates one level; a calm pass
        counts toward de-escalation (``cooldown`` consecutive calm
        passes step the ladder down one level). Returns the level after
        the pass. Forced mode never moves."""
        if not self.enabled or self._forced is not None:
            return self.level
        overload = any(
            rec.get("anomaly") in OVERLOAD_ANOMALY_KINDS
            for rec in (anomalies or ())
        )
        with self._lock:
            if overload:
                self._calm = 0
                if self._level < self.max_level:
                    self._level += 1
            else:
                self._calm += 1
                if self._calm >= self.cooldown and self._level > 0:
                    self._level -= 1
                    self._calm = 0
            return self._level

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "mode": self.mode,
                "level": self._level,
                "max_level": self.max_level,
                "cooldown": self.cooldown,
                "steps": list(DEGRADE_STEPS[:self._level]),
            }


def downscale_image(image, factor: int = 2):
    """Host-side 2x2 (or ``factor``^2) subsample onto the lower-
    resolution bucket — a strided view's copy, no filtering: the
    degrade path's cost must be ~zero host work. Exemplar boxes are
    normalized coordinates, so they transfer unchanged."""
    import numpy as np

    return np.ascontiguousarray(image[::factor, ::factor])


__all__: List[str] = [
    "DEGRADE_STEPS",
    "OVERLOAD_ANOMALY_KINDS",
    "DegradeController",
    "downscale_image",
]
