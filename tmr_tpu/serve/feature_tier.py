"""Feature tier: backbone-only workers on the fleet lease discipline,
streaming features to match-tier engines over the generalized sink link.

The backbone is ~all of the FLOPs while the match+decode tail is cheap
and pattern-dependent, yet the fused serving path scales both on the
same fleet axis. This module splits them (ROADMAP item 2's
disaggregation half):

- **feature partitions** (one per image size) are leased from the same
  :class:`~tmr_tpu.parallel.leases.LeaseService` state machine the map
  and serve fleets use — :class:`FeatureTier` is the coordinator
  (hello/lease/beat/bye over the fleet control protocol, liveness via
  ``expire_pass``);
- each :class:`FeatureWorker` runs ONLY the backbone program
  (``Predictor._get_backbone_fn`` on ``exec_params()`` — the stored
  int8 tree under TMR_QUANT_STORAGE rides along unchanged) and answers
  ``extract`` round-trips on its data plane, which is a
  :class:`~tmr_tpu.serve.gallery.FeatureSinkServer` composed through
  its ``on_request`` hook (PR 15's data link generalized to an online
  request/response protocol). Every extract is fenced against the
  worker's CURRENTLY held (partition, epoch) — a revoked worker
  answers ``fenced``, never stale features;
- the **match tier** consumes this through
  :class:`FeatureTierClient` — a ``ServeEngine(feature_client=...)``
  then replaces the fused path with heads-only programs fed by remote
  features (the documented heads-path ULP exception vs fused). The
  client's in-flight window (``TMR_FEATURE_TIER_WINDOW``) is the
  backpressure contract: a saturated window FAILS FAST so the engine
  drops to its counted local fallback instead of queueing unboundedly
  on the link. Frames with no live holder (``feature_tier_cold``) and
  fetches that die mid-flight (``feature_fallback_frames``) degrade to
  LOCAL execution — counted, never silent, futures always resolve.

Stale-feature safety rides the wire too: every extract reply carries
the worker predictor's ``feature_stamp()`` (params digest + backbone
formulation) and the client refuses a reply whose stamp differs from
its engine's — a feature worker serving a different checkpoint can
never feed the match tier (counted ``stamp_mismatches``).

Env knobs (lazily read; registered in config.ENV_KNOBS): the
``TMR_ELASTIC_*`` lease-liveness family (shared with every fleet) plus
``TMR_FEATURE_TIER_WINDOW`` and ``TMR_FEATURE_TIER_TIMEOUT_S``.
Proof: tests/test_feature_tier.py (remote-vs-local equality, dead
worker mid-stream, fenced extracts) and scripts/stream_bench.py.
"""

from __future__ import annotations

import os
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tmr_tpu.obs import fleetobs as _fleetobs
from tmr_tpu.parallel.leases import (
    LeasePolicy,
    LeaseService,
    Resource,
    connect_timeout,
    oneshot,
    recv_line,
    send_line,
)
from tmr_tpu.serve.fleet import (
    StubFleetPredictor,
    fleet_policy,
    pack_array,
    unpack_array,
)
from tmr_tpu.serve.gallery import FeatureSinkServer


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------------- partitions
class FeaturePartition(Resource):
    """One feature partition: an image-size bucket. Leased for the
    lifetime of its holder (never settles)."""

    __slots__ = ("size",)

    def __init__(self, index: int, size: int):
        super().__init__(index, f"feat{size}")
        self.size = int(size)


# ------------------------------------------------------------ coordinator
class _TierHandler(socketserver.StreamRequestHandler):
    """Control-plane handler (the fleet _FleetHandler shape): JSON
    lines in/out; EOF with leases held is the kill -9 signature."""

    def handle(self):  # noqa: D102 — protocol loop
        tier = self.server.tier  # type: ignore[attr-defined]
        control_worker = None
        clean = False
        try:
            while True:
                try:
                    msg = recv_line(self.rfile)
                except (OSError, ValueError):
                    break
                if msg is None:
                    break
                if msg.get("op") == "hello":
                    control_worker = msg.get("worker")
                if msg.get("op") == "bye":
                    clean = True
                reply = tier.dispatch(msg)
                try:
                    send_line(self.connection, reply)
                except OSError:
                    break
                if clean:
                    break
        finally:
            if control_worker is not None:
                tier.control_closed(control_worker, clean=clean)


class _TierServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FeatureTier:
    """The feature-tier coordinator: backbone workers lease image-size
    partitions here; match-tier clients resolve the current holder per
    size. One per cluster, usually co-located with the front door."""

    def __init__(self, sizes: Sequence[int], *,
                 policy: Optional[LeasePolicy] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 check_interval_s: Optional[float] = None):
        self.sizes = sorted({int(s) for s in sizes})
        if not self.sizes:
            raise ValueError("a feature tier needs at least one size")
        partitions = [
            FeaturePartition(i, size)
            for i, size in enumerate(self.sizes)
        ]
        self.policy = fleet_policy(policy)
        self._svc = LeaseService(
            partitions, self.policy,
            metrics_prefix="feature_tier", noun="partition",
            key_field="partition",
            history_bound=4096,  # indefinite serving: a flapping
            # worker must not grow the event history forever
        )
        self._partitions = partitions
        self._index_by_size = {p.size: p.index for p in partitions}
        self._host, self._port = host, int(port)
        self._lock = threading.RLock()
        self._worker_addr: Dict[str, Tuple[str, int]] = {}
        self._closed = False
        self._stop_event = threading.Event()
        self._server: Optional[_TierServer] = None
        self._threads: List[threading.Thread] = []
        self._check_s = (
            self.policy.check_interval_s
            if check_interval_s is None else float(check_interval_s)
        )

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        server = _TierServer((self._host, self._port), _TierHandler)
        server.tier = self  # type: ignore[attr-defined]
        threads = [
            threading.Thread(target=server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             name="feature-tier-control", daemon=True),
            threading.Thread(target=self._monitor_loop,
                             name="feature-tier-monitor", daemon=True),
        ]
        with self._lock:
            self._server = server
            self._threads = threads
        self._svc.restart_clock()
        for t in threads:
            t.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            assert self._server is not None, "feature tier not started"
            return self._server.server_address[:2]

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            server = self._server
            threads = list(self._threads)
        self._stop_event.set()
        if server is not None:
            server.shutdown()
            server.server_close()
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))

    def __enter__(self) -> "FeatureTier":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(self._check_s):
            try:
                self._svc.expire_pass()
            except Exception:
                pass  # the liveness loop must survive anything

    # ----------------------------------------------------- control protocol
    def dispatch(self, msg: dict) -> dict:
        op = msg.get("op")
        handler = {
            "hello": self._op_hello,
            "lease": self._op_lease,
            "beat": self._op_beat,
            "fail": self._op_fail,
            "bye": self._op_bye,
            "state": lambda m: self.state(),
        }.get(op)
        if handler is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            return handler(msg)
        except Exception as e:  # protocol must answer, never wedge
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _op_hello(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        # a rejoining stable worker id is ALIVE again (the fleet's
        # sticky-drain rule: poison drain survives a reconnect)
        self._svc.rejoin(wid)
        data_addr = msg.get("data_addr")
        if isinstance(data_addr, (list, tuple)) and len(data_addr) == 2:
            with self._lock:
                self._worker_addr[wid] = (str(data_addr[0]),
                                          int(data_addr[1]))
        return {
            "ok": True,
            "sizes": list(self.sizes),
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
            "partitions": len(self._partitions),
        }

    def _op_lease(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        wait = {"partition": None,
                "wait_s": max(self.policy.check_interval_s, 0.05)}
        verdict, part, epoch = self._svc.select(wid)
        if verdict == "drained":
            return {"partition": None, "drained": True}
        if verdict != "grant":
            return wait  # tiers are never "done" while serving
        if self._svc.install(part, epoch, wid) is None:
            return wait
        return {
            "partition": part.key,
            "index": part.index,
            "epoch": epoch,
            "size": part.size,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
        }

    def _op_beat(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        stale: List[List[int]] = []
        for pair in msg.get("held") or ():
            index, epoch = int(pair[0]), int(pair[1])
            if not self._svc.heartbeat(wid, index, epoch):
                stale.append([index, epoch])
        worker = self._svc.worker_rec(wid)
        return {"ok": True, "stale": stale, "drained": worker.drained}

    def _op_fail(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        res = self._svc.fail(wid, index, epoch, msg.get("causes") or [])
        return {"ok": True, **res}

    def _op_bye(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        self._svc.bye(wid)
        # a clean leaver still releases its partitions for rebalance
        self._svc.revoke_worker(wid, "worker_exit")
        return {"ok": True}

    def control_closed(self, wid: str, clean: bool) -> None:
        self._svc.control_closed(str(wid), clean)

    # ------------------------------------------------------------- resolve
    def holder_for(self, size: int
                   ) -> Optional[Tuple[str, int, int, Tuple[str, int]]]:
        """The live holder of one size's partition as
        ``(worker id, epoch, partition index, data address)`` — or None
        (unknown size, unheld partition, or a holder that never
        registered a data plane)."""
        index = self._index_by_size.get(int(size))
        if index is None:
            return None
        holder = self._svc.holder(index)
        if holder is None:
            return None
        wid, epoch = holder
        with self._lock:
            addr = self._worker_addr.get(wid)
        if addr is None:
            return None
        return wid, epoch, index, addr

    def client(self, predictor: Any = None,
               **kw) -> "FeatureTierClient":
        """A match-tier client over this tier (in-process resolve path:
        the usual deployment co-locates tier + front door). Pass the
        engine's predictor so the stamp fence is armed."""
        return FeatureTierClient(self, predictor=predictor, **kw)

    def state(self) -> dict:
        with self._svc.lock:
            with self._lock:
                return {
                    "ok": True,
                    "partitions": {
                        p.key: {
                            "size": p.size,
                            "status": p.status,
                            "holder": self._svc.holder(p.index),
                        }
                        for p in self._partitions
                    },
                    "workers": {
                        w.wid: {"drained": w.drained, "dead": w.dead}
                        for w in self._svc.workers.values()
                    },
                    "reassignments": [
                        dict(r) for r in self._svc.reassignments
                    ],
                }


# ---------------------------------------------------------------- worker
class FeatureWorker:
    """One backbone-only worker: joins a :class:`FeatureTier`, leases
    size partitions, heartbeats them, and answers fenced ``extract``
    round-trips on its data plane — a
    :class:`~tmr_tpu.serve.gallery.FeatureSinkServer` composed through
    ``on_request`` (the push half of the sink keeps working alongside).

    ``predictor`` needs only the backbone surface:
    ``_get_backbone_fn()`` and ``exec_params()``/``params`` — a full
    mesh-aware int8-storage Predictor and the numpy stub both fit."""

    def __init__(self, coordinator: Tuple[str, int], worker_id: str,
                 predictor, *, data_host: str = "127.0.0.1",
                 data_port: int = 0, timeout: float = 30.0):
        self.worker_id = worker_id
        self._pred = predictor
        self.coordinator = (coordinator[0], int(coordinator[1]))
        self._lock = threading.RLock()
        self._held: Dict[int, int] = {}  # partition index -> epoch
        self._size_by_index: Dict[int, int] = {}
        self._stop_event = threading.Event()
        self._drained = False
        self._coordinator_lost = False
        self._counters = {"extracted": 0, "fenced": 0, "errors": 0}
        self._sink = FeatureSinkServer(
            host=data_host, port=data_port,
            on_request=self._on_request,
        )
        data_addr = self._sink.start()
        self._sock = socket.create_connection(
            self.coordinator, timeout=connect_timeout(min(timeout, 5.0))
        )
        self._sock.settimeout(timeout)
        self._file = self._sock.makefile("rb")
        self._ctl_lock = threading.Lock()
        self.config = self._call({
            "op": "hello",
            "data_addr": list(data_addr[:2]),
        })
        self._hb_interval = float(
            self.config.get("hb_interval_s") or 2.5
        )
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- control
    def _call(self, doc: dict) -> dict:
        doc = dict(doc)
        doc.setdefault("worker", self.worker_id)
        with self._ctl_lock:
            send_line(self._sock, doc)
            reply = recv_line(self._file)
        if reply is None:
            raise ConnectionError("feature-tier coordinator closed the "
                                  "connection")
        return reply

    def start(self) -> "FeatureWorker":
        threads = [
            threading.Thread(target=self._lease_loop,
                             name=f"feat-lease-{self.worker_id}",
                             daemon=True),
            threading.Thread(target=self._beat_loop,
                             name=f"feat-beat-{self.worker_id}",
                             daemon=True),
        ]
        with self._lock:
            self._threads = threads
        for t in threads:
            t.start()
        return self

    def _lease_loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                grant = self._call({"op": "lease"})
            except (ConnectionError, OSError):
                if not self._stop_event.is_set():
                    with self._lock:
                        self._coordinator_lost = True
                return
            if grant.get("drained"):
                with self._lock:
                    self._drained = True
                return
            index = grant.get("index")
            if index is None:
                if self._stop_event.wait(
                    float(grant.get("wait_s", 0.2))
                ):
                    return
                continue
            with self._lock:
                self._held[int(index)] = int(grant["epoch"])
                self._size_by_index[int(index)] = int(grant["size"])

    def _beat_loop(self) -> None:
        while not self._stop_event.wait(self._hb_interval):
            try:
                self._beat_once()
            except (ConnectionError, OSError):
                pass  # missed beats ARE the liveness signal

    def _beat_once(self) -> dict:
        with self._lock:
            held = [[i, e] for i, e in self._held.items()]
        reply = oneshot(self.coordinator, {
            "op": "beat", "worker": self.worker_id, "held": held,
        })
        stale = reply.get("stale") or ()
        with self._lock:
            for index, epoch in stale:
                if self._held.get(int(index)) == int(epoch):
                    del self._held[int(index)]
            if reply.get("drained"):
                self._drained = True
        return reply

    # ---------------------------------------------------------- data plane
    def holds(self, index: int, epoch: int) -> bool:
        with self._lock:
            return self._held.get(int(index)) == int(epoch)

    def _on_request(self, doc: dict, state: dict) -> Optional[dict]:
        """The sink's online-op hook: ``extract`` runs the backbone on
        one frame, fenced against the CURRENTLY held (partition,
        epoch) — a revoked worker answers ``fenced``, never stale
        features. Unknown ops fall through (None) to the sink's
        unknown-op error."""
        if doc.get("op") != "extract":
            return None
        index = int(doc.get("partition", -1))
        epoch = int(doc.get("epoch", -1))
        with _fleetobs.op_span(doc, "feature.extract",
                               partition=index) as span:
            if not self.holds(index, epoch):
                with self._lock:
                    self._counters["fenced"] += 1
                span.set_attr(status="fenced")
                return {"op": "extract", "ok": False,
                        "status": "fenced"}
            try:
                image = unpack_array(doc["image"])
                feats = self._extract(image)
            except Exception as e:
                with self._lock:
                    self._counters["errors"] += 1
                span.set_attr(status="error")
                return {"op": "extract", "ok": False,
                        "status": "error",
                        "message": f"{type(e).__name__}: {e}"}
            with self._lock:
                self._counters["extracted"] += 1
            span.set_attr(status="ok")
            reply = {"op": "extract", "ok": True, "status": "ok",
                     "features": pack_array(feats)}
            stamp = getattr(self._pred, "feature_stamp", None)
            if callable(stamp):
                reply["stamp"] = list(stamp())
            return reply

    def _extract(self, image: np.ndarray) -> np.ndarray:
        """One backbone pass (the tier's ONLY program): the same
        ``_get_backbone_fn`` + ``exec_params`` pair the fused engine
        splits out — int8 storage and bucketed-jit caching included."""
        bb = self._pred._get_backbone_fn()
        exec_params = getattr(self._pred, "exec_params", None)
        params = exec_params() if callable(exec_params) \
            else self._pred.params
        batch = image[None] if image.ndim == 3 else image
        return np.asarray(bb(params, batch))

    # ------------------------------------------------------------ lifecycle
    @property
    def held(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._held)

    @property
    def drained(self) -> bool:
        with self._lock:
            return self._drained

    @property
    def coordinator_lost(self) -> bool:
        with self._lock:
            return self._coordinator_lost

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_event.set()
        try:
            self._call({"op": "bye"})
        except (ConnectionError, OSError):
            pass
        try:  # shutdown-first: unblocks any reader before the close
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._sink.close()
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))


# ---------------------------------------------------------------- client
class _ExtractLink:
    """One persistent extract connection to a feature worker's data
    plane. Round-trips serialize under the link lock (one request in
    flight per connection — TCP ordering pairs each reply with its
    request); concurrency comes from the client's window, not the
    wire."""

    def __init__(self, address: Tuple[str, int], timeout_s: float):
        self.address = (address[0], int(address[1]))
        self.sock = socket.create_connection(
            self.address, timeout=connect_timeout(min(timeout_s, 5.0))
        )
        self.sock.settimeout(timeout_s)
        self.file = self.sock.makefile("rb")
        self._lock = threading.Lock()
        self.dead = False

    def call(self, doc: dict) -> Optional[dict]:
        with self._lock:
            if self.dead:
                return None
            try:
                send_line(self.sock, doc)
                reply = recv_line(self.file)
            except (OSError, ValueError):
                self.dead = True
                return None
            if reply is None:
                self.dead = True
            return reply

    def close(self) -> None:
        # shutdown FIRST (the _WorkerLink deadlock lesson): a reader
        # blocked in the buffered file under the link lock would
        # deadlock a lock-then-close ordering — the shutdown unblocks
        # it, so the lock below frees promptly
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        with self._lock:
            self.dead = True


class FeatureTierClient:
    """The match tier's side of the link — what
    ``ServeEngine(feature_client=...)`` consumes:

    - ``holds(size)``: does a live worker hold this size's partition
      (with a registered data plane)? Routes the engine's heads-only
      election; False keeps the frame on the counted local fused path.
    - ``fetch(image, digest, size)``: one fenced extract round-trip to
      the current holder. Returns the (1, h, w, C) features, or None
      on ANY failure — dead link, fenced/stale epoch, stamp mismatch,
      saturated window — so the engine's fallback contract (counted
      local execution, futures always resolve) owns every error path.

    Backpressure is the window semaphore (``window`` argument ->
    ``TMR_FEATURE_TIER_WINDOW``, default 4): at saturation ``fetch``
    fails FAST instead of queueing — local fallback beats an unbounded
    line at a hot worker. ``TMR_FEATURE_TIER_TIMEOUT_S`` (default 10)
    bounds each round-trip.
    """

    def __init__(self, tier: FeatureTier, *, predictor: Any = None,
                 window: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        self._tier = tier
        fstamp = getattr(predictor, "feature_stamp", None)
        self._expect_stamp: Optional[tuple] = (
            tuple(fstamp()) if callable(fstamp) else None
        )
        self._window_n = max(
            _env_int("TMR_FEATURE_TIER_WINDOW", 4)
            if window is None else int(window), 1,
        )
        self._window = threading.BoundedSemaphore(self._window_n)
        self._timeout_s = (
            _env_float("TMR_FEATURE_TIER_TIMEOUT_S", 10.0)
            if timeout_s is None else float(timeout_s)
        )
        self._lock = threading.Lock()
        self._links: Dict[str, _ExtractLink] = {}
        self._counters = {
            "fetches": 0, "fetched": 0, "no_holder": 0,
            "window_rejections": 0, "link_failures": 0, "fenced": 0,
            "errors": 0, "stamp_mismatches": 0,
        }

    def _bump(self, name: str) -> None:
        with self._lock:
            self._counters[name] += 1

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def holds(self, size: int) -> bool:
        return self._tier.holder_for(size) is not None

    def _link_for(self, wid: str,
                  addr: Tuple[str, int]) -> Optional[_ExtractLink]:
        with self._lock:
            link = self._links.get(wid)
        if link is not None and not link.dead \
                and link.address == (addr[0], int(addr[1])):
            return link
        try:
            fresh = _ExtractLink(addr, self._timeout_s)
        except OSError:
            return None
        with self._lock:
            old = self._links.get(wid)
            self._links[wid] = fresh
        if old is not None:
            old.close()
        return fresh

    def fetch(self, image, digest: str, size: int
              ) -> Optional[np.ndarray]:
        self._bump("fetches")
        resolved = self._tier.holder_for(size)
        if resolved is None:
            self._bump("no_holder")
            return None
        wid, epoch, index, addr = resolved
        if not self._window.acquire(blocking=False):
            # backpressure: fail fast at a saturated window — the
            # engine's local fallback beats queueing on the link
            self._bump("window_rejections")
            return None
        try:
            link = self._link_for(wid, addr)
            if link is None:
                self._bump("link_failures")
                return None
            doc = {
                "op": "extract", "partition": index, "epoch": epoch,
                "digest": str(digest), "image": pack_array(image),
            }
            root = _fleetobs.root_span("feature.fetch", size=int(size),
                                       worker=wid)
            if root is not None:
                # the extract front door mints its own trace (the
                # calling engine has no wire ctx to thread through)
                doc["ctx"] = root.ctx()
            try:
                reply = link.call(doc)
            finally:
                if root is not None:
                    root.close()
            if reply is None:
                self._bump("link_failures")
                return None
            if reply.get("ok") is not True:
                self._bump("fenced" if reply.get("status") == "fenced"
                           else "errors")
                return None
            stamp = reply.get("stamp")
            if self._expect_stamp is not None and stamp is not None \
                    and tuple(stamp) != self._expect_stamp:
                # a worker serving a different checkpoint/formulation
                # must never feed this engine's caches
                self._bump("stamp_mismatches")
                return None
            feats = unpack_array(reply["features"])
            self._bump("fetched")
            return feats
        finally:
            self._window.release()

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()


# ------------------------------------------------------------------ stub
class StubFeaturePredictor(StubFleetPredictor):
    """The fleet stub with a REAL data path through its features: the
    backbone embeds each image's mean signature into every feature
    cell, and the heads derive ``scores[:, 0]`` back out of the
    features (bitwise — constant-array means are exact in float32).
    Remote-vs-local equality through this stub is therefore a genuine
    end-to-end check of the disaggregated data path: crossed wires,
    stale features, or a dropped row all show as signature
    mismatches, unlike the base stub whose features are zeros."""

    def feature_stamp(self) -> tuple:
        return ("stub-params", "stub-backbone")

    def _get_backbone_fn(self):
        def bb(p, image):
            arr = np.asarray(image, np.float32)
            b = arr.shape[0]
            sig = arr.reshape(b, -1).mean(axis=1)
            return np.tile(
                sig.reshape(b, 1, 1, 1), (1, 2, 2, 4)
            ).astype(np.float32)
        return bb

    def _get_heads_fn(self, capacity, size):
        def heads(p, rp, feats, ex):
            f = np.asarray(feats, np.float32)
            b = f.shape[0]
            sig = f.reshape(b, -1).mean(axis=1)
            if self.delay_s:
                time.sleep(self.delay_s)
            dets = self._dets(np.zeros((b, 1, 1, 3), np.float32))
            dets["scores"][:, 0] = sig
            return dets
        return heads
