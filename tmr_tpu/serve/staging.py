"""Host->device staging for the serving pipeline.

One formed batch becomes a :class:`StagedBatch`: host-side padding/stacking
(a ragged tail pads up to its power-of-two sub-bucket — see ``_pad_to`` —
with zero images and a dummy exemplar; padded rows compute garbage that
unpadding drops, real rows are untouched, which is what keeps batched
results bitwise-identical to sequential calls) followed by
``jax.device_put`` onto the next device in a
round-robin over the engine's device list. The engine runs this on a
dedicated staging thread feeding a depth-2 queue, so batch N+1's H2D copy
overlaps batch N's device compute (double buffering), and successive
batches land on different chips for data-parallel multi-device serving —
the eval path is embarrassingly parallel, no collective involved.

Params are replicated lazily: the first batch staged for a device pays one
params transfer; every later batch reuses the committed copy.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, List, Sequence

import numpy as np

from tmr_tpu import obs
from tmr_tpu.serve.batcher import Request

#: dummy exemplar box for padded slots — any in-range box works (the rows
#: are dropped at unpad); mid-image keeps select_capacity_bucket happy
_PAD_BOX = (0.45, 0.45, 0.55, 0.55)


@dataclass
class StagedBatch:
    bucket: tuple
    requests: List[Request]
    device: Any  # a jax device (legacy round-robin) or a MeshTarget
    images: Any = None  # device (B, S, S, 3) f32; None for pure-hit heads
    exemplars: Any = None  # device (B, K, 4) f32
    k_real: Any = None  # device (B,) i32 (multi path)
    features: Any = None  # device (B, h, w, C) (heads path, after fill)
    fill_index: List[int] = field(default_factory=list)  # rows needing bb
    padded_slots: int = 0
    t_staged: float = 0.0

    @property
    def target(self):
        """The MeshTarget this batch stages onto (None on the legacy
        per-device path)."""
        from tmr_tpu.serve.meshplan import MeshTarget

        return self.device if isinstance(self.device, MeshTarget) else None


def _pad_to(n: int, bound: int) -> int:
    """Ragged-tail batch shape: the next power of two >= n, capped at the
    bucket's bound. A lone timeout-flushed request must not pay a full
    bound-sized execution (it collapses low-offered-load capacity and the
    p99 bound), so tails run in power-of-two sub-buckets — at most
    log2(bound) extra compiles per bucket, each shape compiled lazily on
    first occurrence, and per-image results stay bitwise-identical (the
    programs are batch-invariant per row; tests/test_serve.py)."""
    p = 1
    while p < n:
        p *= 2
    return min(max(p, 1), max(bound, n))


class DeviceStager:
    """Round-robin device placement + lazy per-device params replication.

    Mesh serving (a ``meshplan.MeshPlan`` on the engine) routes through
    the same stager with :class:`MeshTarget` targets instead of bare
    devices: params commit once per target — sharded over the group's
    ``tp`` axis for tensor-parallel targets
    (``parallel/sharding.serve_param_shardings``), replicated across the
    mesh for the data-parallel target — and batches stage with the
    matching NamedSharding so the program's in_shardings are satisfied
    without a resharding copy at dispatch."""

    def __init__(self, devices: Sequence[Any], params, refiner_params=None):
        if not devices:
            raise ValueError("DeviceStager needs at least one device")
        self.devices = list(devices)
        self._rr = itertools.cycle(self.devices)
        self._host_params = (params, refiner_params)
        self._per_device: dict = {}
        self._lock = threading.Lock()

    def params_for(self, device):
        """(params, refiner_params) committed to ``device`` — a jax
        device or a MeshTarget — cached per placement."""
        from tmr_tpu.serve.meshplan import MeshTarget

        if isinstance(device, MeshTarget):
            return self._params_for_target(device)
        with self._lock:
            if device not in self._per_device:
                import jax

                self._per_device[device] = jax.device_put(
                    self._host_params, device
                )
            return self._per_device[device]

    def _params_for_target(self, target):
        with self._lock:
            placed = self._per_device.get(target.key)
        if placed is not None:
            return placed
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        params, rparams = self._host_params
        if target.tp > 1:
            from tmr_tpu.parallel.sharding import serve_param_shardings

            pshard = serve_param_shardings(params, target.mesh)
            repl = NamedSharding(target.mesh, P())
            placed = (
                jax.device_put(params, pshard),
                None if rparams is None else jax.device_put(rparams, repl),
            )
        elif target.mode == "dp":
            repl = NamedSharding(target.mesh, P())
            placed = (
                jax.device_put(params, repl),
                None if rparams is None else jax.device_put(rparams, repl),
            )
        else:  # tp == 1 replica group: the plain per-device program
            placed = jax.device_put(self._host_params, target.primary)
        with self._lock:
            # a racing double-place commits the same values twice; the
            # second result wins and the first is garbage-collected
            self._per_device[target.key] = placed
        return placed

    def batch_sharding(self, target):
        """How a staged batch array lands on ``target``: sharded over
        ``dp`` for the data-parallel target, replicated across the
        group for tensor-parallel ones, the primary device for plain
        (tp == 1) groups."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if target.mode == "dp":
            return NamedSharding(target.mesh, P("dp"))
        if target.tp > 1:
            return NamedSharding(target.mesh, P())
        return target.primary

    def next_device(self):
        return next(self._rr)

    # ------------------------------------------------------------- staging
    def stage(self, bucket: tuple, requests: List[Request],
              bound: int, target=None) -> StagedBatch:
        """Pad/stack the batch host-side and start its H2D transfers.

        ``bound`` is the PER-DEVICE coalescing bound. With a MeshTarget
        the padded batch additionally respects the target's geometry: a
        data-parallel target pads to ``dp x`` a power-of-two per-shard
        sub-bucket (every shard sees a ladder shape, so dp serving
        compiles the same log2(bound) program set per bucket as the
        unsharded engine — and shards divide evenly by construction)."""
        import time

        import jax

        kind, size, _cap, k = bucket
        n = len(requests)
        if target is not None and target.mode == "dp":
            per_shard = _pad_to((n + target.dp - 1) // target.dp,
                                int(bound))
            bound = per_shard * target.dp
            device = target
            placement = self.batch_sharding(target)
        elif target is not None:
            bound = _pad_to(n, int(bound))
            device = target
            placement = self.batch_sharding(target)
        else:
            bound = _pad_to(n, int(bound))
            device = self.next_device()
            placement = device
        staged = StagedBatch(bucket=bucket, requests=list(requests),
                             device=device,
                             padded_slots=bound - n)

        t_assemble = time.perf_counter()
        if kind == "heads":
            t_put = self._stage_heads(
                staged, bound, size, k,
                target.primary if target is not None else device,
            )
        else:
            images = np.zeros((bound, size, size, 3), np.float32)
            exemplars = np.tile(
                np.asarray(_PAD_BOX, np.float32), (bound, k, 1)
            )
            for i, r in enumerate(requests):
                images[i] = r.image
                exemplars[i] = r.exemplars
            if kind == "multi":
                k_real = np.ones((bound,), np.int32)
                for i, r in enumerate(requests):
                    k_real[i] = r.k_real
            t_put = time.perf_counter()
            staged.images = jax.device_put(images, placement)
            staged.exemplars = jax.device_put(exemplars, placement)
            if kind == "multi":
                staged.k_real = jax.device_put(k_real, placement)
        staged.t_staged = time.perf_counter()
        if obs.tracing_enabled():
            # batch-level windows attributed to each rider: host pad/stack
            # (assemble) then the H2D transfers (stage), same trace id the
            # request carried from submit
            for r in requests:
                tid = r.trace_id or None
                obs.add_span("serve.batch_assemble", t_assemble, t_put,
                             trace_id=tid, bucket=str(bucket),
                             batch=len(requests), padded=staged.padded_slots)
                obs.add_span("serve.stage", t_put, staged.t_staged,
                             trace_id=tid, device=str(device))
        return staged

    def _stage_heads(self, staged: StagedBatch, bound: int, size: int,
                     k: int, device) -> float:
        """Heads-path staging: requests with cached features move only
        their (tiny) exemplars; promotion fills move their image so the
        dispatch thread can run the encoder for them. Cached features may
        live on a different device (round-robin) — device_put moves them,
        a no-op when already resident. Returns the host-assembly ->
        device-transfer boundary timestamp (the stage-span split)."""
        import jax
        import time

        requests = staged.requests
        exemplars = np.tile(
            np.asarray(_PAD_BOX, np.float32), (bound, k, 1)
        )
        for i, r in enumerate(requests):
            exemplars[i] = r.exemplars
        staged.fill_index = [
            i for i, r in enumerate(requests) if r.features is None
        ]
        images = None
        if staged.fill_index:
            # fills pad to a power-of-two sub-bucket like every other
            # batch shape: the backbone program must compile at log2(bound)
            # shapes, not once per distinct fill count — an encoder
            # retrace at serving time is seconds of injected latency
            n_fill = _pad_to(len(staged.fill_index), bound)
            images = np.zeros((n_fill, size, size, 3), np.float32)
            for j, i in enumerate(staged.fill_index):
                images[j] = requests[i].image
        t_put = time.perf_counter()
        staged.exemplars = jax.device_put(exemplars, device)
        if images is not None:
            staged.images = jax.device_put(images, device)
        # hits: move each (1, h, w, C) feature to this batch's device
        staged.features = [
            None if r.features is None else jax.device_put(r.features,
                                                           device)
            for r in requests
        ]
        return t_put
