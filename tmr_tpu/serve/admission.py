"""SLO-aware admission control for the serving engine.

The PR 3 engine queues unboundedly and treats every request alike: at a
5x traffic spike the batcher backlog grows without limit and every
admitted request's latency collapses together. This module is the
bounded front door (ROADMAP item 3):

- :class:`RejectedError` — the structured early-rejection carried by a
  rejected request's future: a closed-vocabulary ``cause``
  (:data:`REJECTION_CAUSES`), the request's ``priority`` class, and a
  ``retry_after_s`` hint derived from the measured drain rate, so a
  client can back off intelligently instead of parsing messages.
- :class:`AdmissionController` — a token/queue-depth controller the
  engine consults in ``submit`` BEFORE any work is done for the
  request: a total in-system bound (``TMR_ADMIT_MAX_PENDING``),
  per-priority-class bounds (``TMR_ADMIT_CLASS_PENDING``), and an
  optional token-bucket arrival-rate limit (``TMR_ADMIT_RATE`` /
  ``TMR_ADMIT_BURST``). Disabled (``TMR_ADMIT=0``, the default) the
  whole controller is one bool check and the engine behaves exactly
  like PR 3 — unbounded queues, no rejection.

Accounting contract: every admitted request occupies exactly one
admission slot from ``try_admit`` until its ONE terminal event (resolve,
fail, shed, or shutdown rejection); ``release`` is idempotent per
request, so the reject + shed + complete + error tallies reconcile
exactly with submissions (scripts/overload_probe.py proves this at 5x
offered load).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

#: closed rejection-cause vocabulary carried by RejectedError (and the
#: overload probe's per-cause tallies): "queue_full" = the total
#: in-system bound tripped, "class_limit" = this priority class's bound
#: tripped, "rate_limited" = the token bucket ran dry, "deadline" = the
#: request's deadline expired before a pipeline stage would have spent
#: device time on it (shed), "shutdown" = the engine closed before the
#: request could be served (bounded-drain rejection), "worker_lost" =
#: the fleet front door (serve/fleet.py) exhausted its resubmission
#: bound after the request's serve worker died/was fenced — terminal,
#: never silently retried past the bound.
REJECTION_CAUSES = (
    "queue_full",
    "class_limit",
    "rate_limited",
    "deadline",
    "shutdown",
    "worker_lost",
)


class RejectedError(RuntimeError):
    """A request the engine declined to serve, with machine-readable why.

    ``cause`` is one of :data:`REJECTION_CAUSES`; ``priority`` the
    request's class; ``retry_after_s`` a positive backoff hint when the
    condition is transient (queue/rate pressure), None when retrying is
    pointless (shutdown).
    """

    def __init__(self, cause: str, message: str, *, priority: int = 0,
                 retry_after_s: Optional[float] = None):
        assert cause in REJECTION_CAUSES, cause
        super().__init__(message)
        self.cause = cause
        self.priority = int(priority)
        self.retry_after_s = (
            None if retry_after_s is None else round(float(retry_after_s), 3)
        )

    def record(self) -> dict:
        """The gate_refused-style cause record (one dict, no message
        parsing needed downstream)."""
        return {
            "cause": self.cause,
            "priority": self.priority,
            "retry_after_s": self.retry_after_s,
            "message": str(self),
        }


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0", "false", "off")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int_list(name: str) -> List[int]:
    out: List[int] = []
    for part in os.environ.get(name, "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.append(int(part))
        except ValueError:
            return []
    return out


def parse_class_weights(spec: str = "") -> Sequence[float]:
    """``TMR_ADMIT_CLASS_WEIGHTS`` parser: comma-separated positive
    floats indexed by priority class; class beyond the list reuses the
    last entry. Empty/invalid -> the default doubling ladder (class 0
    weight 1, each higher class twice the previous)."""
    spec = spec or os.environ.get("TMR_ADMIT_CLASS_WEIGHTS", "")
    weights: List[float] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            weights = []
            break
        if w <= 0:
            weights = []
            break
        weights.append(w)
    return tuple(weights) or (1.0, 2.0, 4.0, 8.0)


def class_weight_fn(spec: str = ""):
    """A ``priority -> weight`` callable over :func:`parse_class_weights`
    (the MicroBatcher's pop-ordering input)."""
    weights = parse_class_weights(spec)

    def weight(priority: int) -> float:
        p = max(int(priority), 0)
        return weights[min(p, len(weights) - 1)]

    return weight


class AdmissionController:
    """Bounded admission with per-class depth limits and a token bucket.

    All state lives under one lock: the submit path (any caller thread)
    admits, and every pipeline thread releases at a request's terminal
    event. Releases also feed a small timestamp window that estimates
    the engine's drain rate — the ``retry_after_s`` hint on a rejection
    is ``excess / drain_rate``, i.e. "by when will a slot plausibly be
    free", not a magic constant.
    """

    def __init__(self, *, enabled: Optional[bool] = None,
                 max_pending: Optional[int] = None,
                 class_pending: Optional[Sequence[int]] = None,
                 rate: Optional[float] = None,
                 burst: Optional[int] = None):
        self.enabled = _env_flag("TMR_ADMIT") if enabled is None \
            else bool(enabled)
        self.max_pending = (
            _env_int("TMR_ADMIT_MAX_PENDING", 256)
            if max_pending is None else int(max_pending)
        )
        cp = (_env_int_list("TMR_ADMIT_CLASS_PENDING")
              if class_pending is None else list(class_pending))
        self.class_pending = tuple(int(x) for x in cp)
        self.rate = _env_float("TMR_ADMIT_RATE", 0.0) if rate is None \
            else float(rate)
        self.burst = (
            max(_env_int("TMR_ADMIT_BURST", 16), 1)
            if burst is None else max(int(burst), 1)
        )
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._total = 0
        self._tokens = float(self.burst)
        self._t_tokens = time.monotonic()
        self._releases: deque = deque(maxlen=64)
        self._rejected: Dict[str, int] = {}
        #: measured-capacity override (ServeEngine wires its summed
        #: per-replica-group drain rate here under a mesh plan)
        self._drain_source = None

    def attach_drain_source(self, fn) -> None:
        """Use ``fn() -> requests/s`` as the measured drain-rate signal.

        Under a mesh plan the engine completes work on several replica
        groups concurrently; its summed per-group drain rate is the real
        multi-chip capacity, while this controller's internal release
        window is a single aggregate that lags a fleet of pipelines.
        The source must never take locks that can wait on this
        controller (the engine's window uses its own dedicated lock)."""
        with self._lock:
            self._drain_source = fn

    # ----------------------------------------------------------- helpers
    def _class_bound(self, priority: int) -> int:
        """Per-class in-system bound: the ``TMR_ADMIT_CLASS_PENDING``
        entry for this class (classes beyond the list reuse the last
        entry); no list -> the total bound applies per class too."""
        if not self.class_pending:
            return self.max_pending
        p = max(int(priority), 0)
        return self.class_pending[min(p, len(self.class_pending) - 1)]

    def _drain_rate_unlocked(self) -> float:
        """Measured drain rate: the attached engine source (summed
        per-replica-group rates under a mesh plan, the fleet's summed
        per-worker beats) when it yields a positive FINITE number, else
        releases per second over the recent release window (0.0 when
        fewer than two releases have ever been observed). A source that
        raises, returns 0/negative/non-finite, or has gone stale (the
        engine/fleet side reports 0 once its completion window ages
        out) therefore always falls back to the window estimate —
        pinned by tests/test_overload.py."""
        if self._drain_source is not None:
            try:
                rate = float(self._drain_source())
                if rate > 0 and math.isfinite(rate):
                    return rate
            except Exception:
                pass  # a broken source falls back to the window
        if len(self._releases) < 2:
            return 0.0
        span = self._releases[-1] - self._releases[0]
        if span <= 0:
            return 0.0
        return (len(self._releases) - 1) / span

    def _retry_after_unlocked(self, excess: int) -> Optional[float]:
        rate = self._drain_rate_unlocked()
        if rate <= 0:
            return 1.0  # no drain evidence yet: a modest fixed backoff
        return min(max(excess / rate, 0.05), 60.0)

    # ------------------------------------------------------------ admit
    def try_admit(self, priority: int = 0) -> Optional[RejectedError]:
        """One admission decision. None = admitted (a slot is now held
        and MUST be released exactly once via :meth:`release` /
        :meth:`release_class`); a :class:`RejectedError` = rejected, no
        slot held."""
        if not self.enabled:
            return None
        priority = max(int(priority), 0)
        with self._lock:
            if self.rate > 0:
                now = time.monotonic()
                self._tokens = min(
                    float(self.burst),
                    self._tokens + (now - self._t_tokens) * self.rate,
                )
                self._t_tokens = now
                if self._tokens < 1.0:
                    self._rejected["rate_limited"] = (
                        self._rejected.get("rate_limited", 0) + 1
                    )
                    return RejectedError(
                        "rate_limited",
                        f"arrival rate over TMR_ADMIT_RATE={self.rate}",
                        priority=priority,
                        retry_after_s=(1.0 - self._tokens) / self.rate,
                    )
            if self._total >= self.max_pending:
                self._rejected["queue_full"] = (
                    self._rejected.get("queue_full", 0) + 1
                )
                return RejectedError(
                    "queue_full",
                    f"{self._total} requests in system (bound "
                    f"{self.max_pending})",
                    priority=priority,
                    retry_after_s=self._retry_after_unlocked(
                        self._total - self.max_pending + 1
                    ),
                )
            bound = self._class_bound(priority)
            held = self._counts.get(priority, 0)
            if held >= bound:
                self._rejected["class_limit"] = (
                    self._rejected.get("class_limit", 0) + 1
                )
                return RejectedError(
                    "class_limit",
                    f"priority class {priority} holds {held} slots "
                    f"(bound {bound})",
                    priority=priority,
                    retry_after_s=self._retry_after_unlocked(
                        held - bound + 1
                    ),
                )
            if self.rate > 0:
                self._tokens -= 1.0
            self._counts[priority] = held + 1
            self._total += 1
        return None

    def release_class(self, priority: int) -> None:
        """Give back one slot for ``priority`` (the pre-Request paths:
        cache hit, coalesce, malformed — the request object never
        carried the slot)."""
        if not self.enabled:
            return
        priority = max(int(priority), 0)
        with self._lock:
            held = self._counts.get(priority, 0)
            if held > 0:
                self._counts[priority] = held - 1
                self._total -= 1
                self._releases.append(time.monotonic())

    def release(self, req) -> None:
        """Terminal-event release for an enqueued Request — idempotent:
        the ``admitted`` flag flips under this controller's lock, so
        whichever pipeline stage reaches the request's terminal event
        first releases, and every later caller no-ops."""
        if not self.enabled:
            return
        with self._lock:
            if not getattr(req, "admitted", False):
                return
            req.admitted = False
            priority = max(int(req.priority), 0)
            held = self._counts.get(priority, 0)
            if held > 0:
                self._counts[priority] = held - 1
                self._total -= 1
                self._releases.append(time.monotonic())

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "max_pending": self.max_pending,
                "class_pending": list(self.class_pending),
                "rate": self.rate,
                "burst": self.burst,
                "in_system": self._total,
                "per_class": dict(self._counts),
                "drain_per_sec": round(self._drain_rate_unlocked(), 3),
                "rejected": dict(self._rejected),
            }
