"""Content-addressed LRU caches for the serving layer.

Two instances back the engine (tmr_tpu/serve/engine.py):

- the **exemplar/result cache** — keyed by (image digest, exemplar bytes,
  bucket), holding finished per-request detections. Interactive users
  re-querying the same pattern on the same image skip the device entirely,
  and the stored result is the bytes the original request returned, so a
  hit is bitwise-identical by construction.
- the **image-feature cache** — keyed by (image digest, image size),
  holding the encoder's pre-upsample feature map ON DEVICE. The
  multi-query-same-image pattern re-runs only the matcher/head tail
  (Predictor._get_heads_fn) against it.

Both expose hit/miss/eviction/insert counters (``stats()``) — the serve
report's cache section — and are thread-safe: the engine's submit path and
its completion thread touch them concurrently. The counters live in the
obs metrics registry when one is passed (the engine passes its own, so a
``metrics_report/v1`` snapshot carries cache state under
``<name>.hits/...``); a bare ``LRUCache(n)`` keeps standalone counters.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional

import numpy as np

from tmr_tpu.obs.metrics import Counter, MetricsRegistry


def array_digest(*arrays) -> str:
    """Content digest of numpy arrays: dtype + shape + bytes, so two
    logically different tensors that share a byte pattern (e.g. a (4,)
    f32 vs a (16,) u8) can never collide onto one key."""
    h = hashlib.sha1()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def value_nbytes(value) -> int:
    """Best-effort byte size of a cached value: arrays (numpy or jax —
    anything with ``.nbytes``) count their buffer, containers sum their
    leaves, everything else counts zero. Zero-on-unknown keeps the byte
    bound conservative-in-one-direction only for exotic values; every
    value the serving layer actually caches (feature maps, detection
    dicts) is array-shaped and counts exactly."""
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        try:
            return int(nbytes)
        except (TypeError, ValueError):
            return 0
    if isinstance(value, dict):
        return sum(value_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(value_nbytes(v) for v in value)
    return 0


class LRUCache:
    """Bounded LRU mapping with observable counters.

    ``capacity <= 0`` constructs a disabled cache: every ``get`` misses,
    ``put`` is a no-op — callers never need an "is caching on" branch.

    ``registry``/``name``: when given, the hit/miss/eviction/insert
    counters are registered as ``<name>.hits`` etc. in that
    MetricsRegistry (they then travel in its ``snapshot()``); otherwise
    the cache keeps private Counter instances. ``stats()`` reads the same
    shape either way.

    ``max_bytes``: optional RESIDENCY bound on top of the entry-count
    bound — the on-device feature cache holds whole feature maps, so a
    count-only bound lets large frames blow HBM invisibly
    (``TMR_SERVE_FEATURE_CACHE_MB`` wires this on the engine; gallery
    banks size theirs the same way). When set, inserts evict LRU-first
    until the tracked total fits; an entry ALONE bigger than the bound
    is dropped up front without disturbing the resident working set
    (insert + eviction both counted — observable, never a silent
    no-op), and ``stats()`` additionally reports ``bytes`` /
    ``max_bytes``. Unset (0/None) keeps the count-only behavior and the
    original stats shape byte-identical.
    """

    def __init__(self, capacity: int,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "",
                 max_bytes: Optional[int] = None):
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes) if max_bytes else 0
        self._data: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._sizes: dict = {}
        self._bytes = 0
        self._lock = threading.Lock()
        if registry is not None and name:
            make = lambda which: registry.counter(f"{name}.{which}")  # noqa: E731
        else:
            make = lambda which: Counter()  # noqa: E731
        self._hits = make("hits")
        self._misses = make("misses")
        self._evictions = make("evictions")
        self._inserts = make("inserts")

    # counter VALUES as attributes, back-compat with the PR 3 plain-int
    # fields (diagnostic consumers read cache.hits directly)
    @property
    def hits(self) -> int:
        return self._hits.value

    @property
    def misses(self) -> int:
        return self._misses.value

    @property
    def evictions(self) -> int:
        return self._evictions.value

    @property
    def inserts(self) -> int:
        return self._inserts.value

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self._hits.inc()
                return self._data[key]
            self._misses.inc()
            return None

    def put(self, key: Hashable, value: Any) -> None:
        if self.capacity <= 0:
            return
        size = value_nbytes(value) if self.max_bytes else 0
        with self._lock:
            if self.max_bytes and size > self.max_bytes:
                # an entry alone over the bound is dropped WITHOUT
                # touching the resident working set (evicting hot
                # entries to make room for something that can never fit
                # would wipe the cache); counted as insert + eviction so
                # the drop is observable, and a previous value under
                # the same key is removed — the caller replaced it
                if key in self._data:
                    self._bytes -= self._sizes.pop(key, 0)
                    del self._data[key]
                self._inserts.inc()
                self._evictions.inc()
                return
            if key in self._data:
                self._data.move_to_end(key)
                self._bytes -= self._sizes.pop(key, 0)
            self._data[key] = value
            if self.max_bytes:
                self._sizes[key] = size
                self._bytes += size
            self._inserts.inc()
            while self._data and (
                len(self._data) > self.capacity
                or (self.max_bytes and self._bytes > self.max_bytes)
            ):
                dead, _ = self._data.popitem(last=False)
                self._bytes -= self._sizes.pop(dead, 0)
                self._evictions.inc()

    def pop(self, key: Hashable) -> Optional[Any]:
        """Remove one entry (byte accounting updated); returns the value
        or None when absent. A bookkeeping operation like
        ``__contains__`` — it touches neither the traffic counters nor
        the eviction tally (evictions count capacity pressure, not
        explicit removals)."""
        with self._lock:
            if key not in self._data:
                return None
            self._bytes -= self._sizes.pop(key, 0)
            return self._data.pop(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key: Hashable) -> bool:
        """Presence probe that does NOT touch the hit/miss counters (or
        recency) — bookkeeping lookups must not masquerade as traffic."""
        with self._lock:
            return key in self._data

    def stats(self) -> dict:
        with self._lock:
            hits, misses = self._hits.value, self._misses.value
            total = hits + misses
            out = {
                "capacity": self.capacity,
                "size": len(self._data),
                "hits": hits,
                "misses": misses,
                "evictions": self._evictions.value,
                "inserts": self._inserts.value,
                "hit_rate": (hits / total) if total else 0.0,
            }
            if self.max_bytes:
                # present only under byte accounting: the default stats
                # shape stays byte-identical (engine/report pins)
                out["bytes"] = self._bytes
                out["max_bytes"] = self.max_bytes
            return out
