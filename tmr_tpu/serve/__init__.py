"""Throughput serving layer over the bucketed fused inference programs.

``ServeEngine`` is the entry point: dynamic micro-batching under a latency
bound, content-addressed exemplar/feature caches, and pipelined
round-robin multi-device dispatch — see engine.py for the architecture and
contracts, scripts/serve_bench.py for the measured proof.
"""

from tmr_tpu.serve.admission import (
    REJECTION_CAUSES,
    AdmissionController,
    RejectedError,
    class_weight_fn,
)
from tmr_tpu.serve.batcher import MicroBatcher, Request
from tmr_tpu.serve.caches import LRUCache, array_digest
from tmr_tpu.serve.degrade import DEGRADE_STEPS, DegradeController
from tmr_tpu.serve.engine import ServeEngine
from tmr_tpu.serve.feature_tier import (
    FeaturePartition,
    FeatureTier,
    FeatureTierClient,
    FeatureWorker,
    StubFeaturePredictor,
)
from tmr_tpu.serve.fleet import (
    FleetWorker,
    ServeFleet,
    StubFleetPredictor,
    stub_engine,
    stub_signature,
)
from tmr_tpu.serve.gallery import (
    FeatureSinkServer,
    GalleryBank,
    gallery_fused_ok,
)
from tmr_tpu.serve.meshplan import MeshPlan, MeshTarget, resolve_plan
from tmr_tpu.serve.staging import DeviceStager, StagedBatch
from tmr_tpu.serve.streams import StreamRouter, block_signature

__all__ = [
    "AdmissionController",
    "DEGRADE_STEPS",
    "DegradeController",
    "DeviceStager",
    "FeaturePartition",
    "FeatureSinkServer",
    "FeatureTier",
    "FeatureTierClient",
    "FeatureWorker",
    "FleetWorker",
    "GalleryBank",
    "LRUCache",
    "MeshPlan",
    "MeshTarget",
    "MicroBatcher",
    "REJECTION_CAUSES",
    "RejectedError",
    "Request",
    "ServeEngine",
    "ServeFleet",
    "StagedBatch",
    "StreamRouter",
    "StubFeaturePredictor",
    "StubFleetPredictor",
    "array_digest",
    "block_signature",
    "class_weight_fn",
    "gallery_fused_ok",
    "resolve_plan",
    "stub_engine",
    "stub_signature",
]
