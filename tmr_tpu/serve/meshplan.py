"""Mesh execution plan for the serving tier: replica groups, per-bucket
mode selection, and the dispatch targets sharded programs compile
against.

``TMR_SERVE_MESH`` (or ``ServeEngine(mesh=...)``) names a device mesh
over the local chips — ``"dp4"``, ``"tp4"``, ``"dp2tp2"`` — with the
axes of ``parallel.mesh.SERVE_AXES``:

- **dp** — data parallelism: one dispatch shards its batch across the
  ``dp`` replica groups (each image computed whole on one group). With
  ``tp == 1`` the program is a ``shard_map`` over ``dp`` whose per-shard
  trace IS the unsharded program body at the local batch shape, so
  per-request results stay bitwise-identical to the unsharded engine.
- **tp** — tensor parallelism inside a replica group: the ViT feature
  dimensions shard over the group's ``tp`` devices (Megatron-style,
  ``parallel/sharding.py`` specs through the GSPMD/pjit path), so ONE
  big image uses every chip in its group. TP collectives reorder float
  reductions, so tp results are allclose-level with identical keep
  decisions (the heads-path precedent), never silently different.

Mode is selected **per bucket**: buckets at or above the
``TMR_SERVE_TP_SIZE`` image size run tensor-parallel on a replica group
(big images — saturate a group per image); smaller buckets fan out
data-parallel across groups (small images — saturate the mesh per
batch). Feature-cached ``heads`` buckets always run per group on the
group's primary device (the split tail is not worth collectives).

The plan is immutable after construction; the engine owns all mutable
scheduling state (per-group queues, round-robin counters).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from tmr_tpu.parallel.mesh import (
    SERVE_AXES,
    make_serve_mesh,
    parse_mesh_spec,
    replica_groups,
)

#: target modes: "group" = one replica group (tensor-parallel when the
#: group has > 1 device, the plain per-device program when tp == 1);
#: "dp" = the full mesh, batch sharded over the dp axis
TARGET_MODES = ("group", "dp")


class MeshTarget:
    """One dispatch target: a mesh (or sub-mesh) plus the batch-axis
    mode a program compiles for. ``key`` is the hashable component the
    sharded ``Predictor._compiled`` entries embed — it names the axis
    sizes AND the concrete device ids, so a mesh-shape change (or a
    different replica group) can never silently collide with a cached
    program built for other devices."""

    def __init__(self, name: str, mode: str, mesh, devices: Sequence[Any]):
        assert mode in TARGET_MODES, mode
        self.name = str(name)
        self.mode = mode
        self.mesh = mesh
        self.devices = tuple(devices)
        shape = dict(mesh.shape)
        self.dp = int(shape.get("dp", 1))
        self.tp = int(shape.get("tp", 1))
        self.key = (
            self.mode,
            tuple(sorted(shape.items())),
            tuple(getattr(d, "id", str(d)) for d in self.devices),
        )

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    @property
    def primary(self):
        """The group's first device — where unsharded programs (the
        feature-cache heads path) execute."""
        return self.devices[0]

    def __repr__(self) -> str:  # per_device_batches / health keys
        return self.name

    __str__ = __repr__


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class MeshPlan:
    """The serving tier's execution plan for one mesh spec.

    ``group_targets`` — one :class:`MeshTarget` per replica group (mode
    "group").  ``dp_target`` — the full-mesh data-parallel target, or
    None when ``dp == 1`` (then every bucket runs on the single group).
    ``mode_for(bucket)`` / ``target_for(bucket, group)`` encode the
    per-bucket replica-group selection documented in the module
    docstring.
    """

    def __init__(self, spec: str, devices: Optional[Sequence[Any]] = None,
                 tp_size: Optional[int] = None):
        self.spec = str(spec).strip().lower()
        self.sizes = parse_mesh_spec(self.spec)
        self.mesh = make_serve_mesh(self.spec, devices=devices)
        self.dp = self.sizes["dp"]
        self.tp = self.sizes["tp"]
        #: image-size floor for tensor-parallel mode (big images go tp);
        #: ignored when the mesh has no usable alternative
        self.tp_size = (
            _env_int("TMR_SERVE_TP_SIZE", 512)
            if tp_size is None else int(tp_size)
        )
        groups = replica_groups(self.mesh)
        self.group_targets: List[MeshTarget] = []
        for i, devs in enumerate(groups):
            sub = make_serve_mesh(f"dp1tp{self.tp}", devices=devs)
            self.group_targets.append(
                MeshTarget(f"group{i}", "group", sub, devs)
            )
        self.dp_target: Optional[MeshTarget] = (
            MeshTarget("dp", "dp", self.mesh,
                       [d for row in groups for d in row])
            if self.dp > 1 else None
        )

    # ------------------------------------------------------------ policy
    def mode_for(self, bucket: tuple) -> str:
        """"dp" or "group" for one bucket key.

        - ``heads`` buckets (feature-cache path) always run per group.
        - With both axes available, image size decides: >= ``tp_size``
          runs tensor-parallel on a group, smaller fans out dp.
        - A pure-dp mesh (tp == 1) sends everything dp except heads; a
          pure-tp mesh (dp == 1) has only the one group.
        """
        if self.dp_target is None:
            return "group"
        kind, size = bucket[0], int(bucket[1])
        if kind == "heads":
            return "group"
        if self.tp > 1 and size >= self.tp_size:
            return "group"
        return "dp"

    def group_ids(self) -> List[Any]:
        """The batcher queue-group ids: one per replica group, plus
        "dp" when the full-mesh target exists."""
        ids: List[Any] = [t.name for t in self.group_targets]
        if self.dp_target is not None:
            ids.append(self.dp_target.name)
        return ids

    def target_by_group(self, group: Any) -> MeshTarget:
        if self.dp_target is not None and group == self.dp_target.name:
            return self.dp_target
        for t in self.group_targets:
            if t.name == group:
                return t
        raise KeyError(f"unknown replica group {group!r}")

    # ---------------------------------------------------------- reporting
    def describe(self) -> Dict[str, Any]:
        """The ``mesh`` attachment serve_report/v1 carries (validated by
        ``diagnostics.validate_serve_report``): spec, axis shape, axis
        names, replica groups by device string, and the mode policy's
        size threshold."""
        return {
            "spec": self.spec,
            "shape": {"dp": self.dp, "tp": self.tp},
            "axis_names": list(SERVE_AXES),
            "replica_groups": [
                [str(d) for d in t.devices] for t in self.group_targets
            ],
            "tp_size_threshold": self.tp_size,
        }


def resolve_plan(mesh: Optional[str],
                 devices: Optional[Sequence[Any]] = None,
                 tp_size: Optional[int] = None) -> Optional[MeshPlan]:
    """The engine's mesh resolution: explicit argument first, then the
    ``TMR_SERVE_MESH`` env knob; empty/unset -> None (the unsharded
    round-robin engine, byte-identical to the pre-mesh behavior)."""
    spec = os.environ.get("TMR_SERVE_MESH", "") if mesh is None else mesh
    spec = (spec or "").strip()
    if not spec or spec in ("0", "off", "none"):
        return None
    return MeshPlan(spec, devices=devices, tp_size=tp_size)
