"""Dynamic micro-batching: coalesce single-image requests into bucket
programs under a latency bound.

The queue discipline (the StreamFlow lesson from PAPERS.md applied to the
eval path): requests accumulate per bucket key (Predictor.bucket_key — one
compiled program per key) and a batch is released when EITHER

- a bucket reaches its size bound (``bound_for(bucket)`` — by default the
  measured throughput-optimal batch from bench_extra's sweep via the
  autotune cache, see engine.py), or
- the OLDEST request in a bucket has waited ``max_wait_ms`` (the latency
  bound: a lone request is never held hostage to batch-filling).

Ragged releases (timeout flushes, close-time drains) are padded up to the
bound by the staging layer so every dispatch hits the one compiled program
shape per bucket.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tmr_tpu import obs


@dataclass
class Request:
    """One in-flight inference request riding the batching pipeline."""

    image: Any  # host (S, S, 3) float32
    exemplars: Any  # host (K, 4) float32 (multi: padded to k_bucket)
    bucket: tuple  # Predictor.bucket_key(...)
    futures: List[Any] = field(default_factory=list)  # resolved together
    t_submit: float = field(default_factory=time.perf_counter)
    k_real: int = 1  # multi path: real exemplar rows
    image_digest: str = ""
    result_key: Optional[tuple] = None  # exemplar/result-cache key
    features: Any = None  # cached device features (heads path, hit)
    needs_features: bool = False  # heads path, promotion fill
    trace_id: str = ""  # per-request span correlation (obs.tracing)

    def resolve(self, value) -> None:
        for f in self.futures:
            if not f.done():
                f.set_result(value)

    def fail(self, exc: BaseException) -> None:
        for f in self.futures:
            if not f.done():
                f.set_exception(exc)


class MicroBatcher:
    """Per-bucket request queue with size- and latency-bounded release.

    ``next_batch()`` blocks until a batch is due and returns
    ``(bucket, [Request, ...])`` — or None once the batcher is closed AND
    drained (the consumer thread's shutdown signal). Thread-safe: any
    number of producers (``put``), one consumer.
    """

    def __init__(self, max_wait_ms: float,
                 bound_for: Callable[[tuple], int]):
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.bound_for = bound_for
        # ordered so the flush scan visits buckets in first-use order —
        # no bucket can be starved behind a constantly-full sibling
        self._pending: "OrderedDict[tuple, deque]" = OrderedDict()
        self._cond = threading.Condition()
        self._closed = False
        #: released-batch size histogram {occupied_slots: count} — the
        #: serve report's batch-occupancy evidence
        self.occupancy: Counter = Counter()

    def put(self, req: Request) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._pending.setdefault(req.bucket, deque()).append(req)
            self._cond.notify()

    def close(self) -> None:
        """Stop accepting; pending requests still drain via next_batch."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _pop(self, bucket: tuple, n: int) -> Tuple[tuple, List[Request]]:
        dq = self._pending[bucket]
        out = [dq.popleft() for _ in range(min(n, len(dq)))]
        if not dq:
            del self._pending[bucket]
        else:
            # rotate a bucket that released but still holds requests to the
            # back of the scan order: a sustained-load bucket must not
            # monopolize rule 2's full-bucket scan while siblings queue
            self._pending.move_to_end(bucket)
        self.occupancy[len(out)] += 1
        if obs.tracing_enabled():
            # queue wait = submit -> release, per request: the window was
            # stamped at submit, so it is recorded retroactively here.
            # Guarded: this runs on the consumer thread OUTSIDE the
            # engine's isolation try blocks — telemetry must never kill
            # the thread that forms batches.
            try:
                now = time.perf_counter()
                for r in out:
                    obs.add_span("serve.queue_wait", r.t_submit, now,
                                 trace_id=r.trace_id or None,
                                 bucket=str(bucket))
            except Exception:
                pass
        return bucket, out

    def next_batch(self) -> Optional[Tuple[tuple, List[Request]]]:
        with self._cond:
            while True:
                # 1. an EXPIRED latency deadline releases first — the
                # max_wait_ms bound holds even while a sibling bucket is
                # kept full by sustained load (full buckets can wait one
                # round; an expired lone request has already waited its
                # contractual maximum)
                now = time.perf_counter()
                deadline = None
                due = None
                for bucket, dq in self._pending.items():
                    t = dq[0].t_submit + self.max_wait_s
                    if deadline is None or t < deadline:
                        deadline, due = t, bucket
                if deadline is not None and now >= deadline:
                    return self._pop(
                        due, max(1, int(self.bound_for(due)))
                    )
                # 2. any full bucket releases immediately (first-use order,
                # rotated by _pop so equals take turns)
                for bucket, dq in self._pending.items():
                    bound = max(1, int(self.bound_for(bucket)))
                    if len(dq) >= bound:
                        return self._pop(bucket, bound)
                if self._closed:
                    # drain: flush partial buckets oldest-first
                    for bucket in self._pending:
                        return self._pop(
                            bucket, max(1, int(self.bound_for(bucket)))
                        )
                    return None
                # 3. else sleep until the earliest deadline (or new work)
                self._cond.wait(
                    timeout=None if deadline is None else deadline - now
                )

    def pending(self) -> int:
        with self._cond:
            return sum(len(d) for d in self._pending.values())

    def depth_snapshot(self) -> Dict[tuple, int]:
        """Per-bucket queue depths right now — the health report's
        queue evidence (``ServeEngine.health()``)."""
        with self._cond:
            return {bucket: len(dq)
                    for bucket, dq in self._pending.items()}

    def occupancy_snapshot(self) -> Dict[int, int]:
        with self._cond:
            return dict(self.occupancy)
