"""Dynamic micro-batching: coalesce single-image requests into bucket
programs under a latency bound.

The queue discipline (the StreamFlow lesson from PAPERS.md applied to the
eval path): requests accumulate per bucket key (Predictor.bucket_key — one
compiled program per key) and a batch is released when EITHER

- a bucket reaches its size bound (``bound_for(bucket)`` — by default the
  measured throughput-optimal batch from bench_extra's sweep via the
  autotune cache, see engine.py), or
- the OLDEST request in a bucket has waited ``max_wait_ms`` (the latency
  bound: a lone request is never held hostage to batch-filling).

Ragged releases (timeout flushes, close-time drains) are padded up to the
bound by the staging layer so every dispatch hits the one compiled program
shape per bucket.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tmr_tpu import obs


@dataclass
class Request:
    """One in-flight inference request riding the batching pipeline."""

    image: Any  # host (S, S, 3) float32
    exemplars: Any  # host (K, 4) float32 (multi: padded to k_bucket)
    bucket: tuple  # Predictor.bucket_key(...)
    futures: List[Any] = field(default_factory=list)  # resolved together
    t_submit: float = field(default_factory=time.perf_counter)
    k_real: int = 1  # multi path: real exemplar rows
    image_digest: str = ""
    result_key: Optional[tuple] = None  # exemplar/result-cache key
    features: Any = None  # cached device features (heads path, hit)
    needs_features: bool = False  # heads path, promotion fill
    trace_id: str = ""  # per-request span correlation (obs.tracing)
    group: Any = None  # replica-group queue id (mesh serving; None = the
    # single ungrouped pipeline, the pre-mesh behavior)
    priority: int = 0  # class-weighted scheduling (higher = sooner)
    deadline: Optional[float] = None  # absolute perf_counter seconds;
    # coalesced duplicates inherit the EARLIEST deadline of the group
    admitted: bool = False  # holds one admission slot until terminal
    degrade_steps: tuple = ()  # ladder steps applied to THIS request

    def expired(self, now: Optional[float] = None) -> bool:
        """Past its deadline? Expired requests are shed by the next
        pipeline stage instead of burning device time."""
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline

    def resolve(self, value) -> None:
        for f in self.futures:
            if not f.done():
                f.set_result(value)

    def fail(self, exc: BaseException) -> None:
        for f in self.futures:
            if not f.done():
                f.set_exception(exc)


class MicroBatcher:
    """Per-bucket request queue with size- and latency-bounded release.

    ``next_batch()`` blocks until a batch is due and returns
    ``(bucket, [Request, ...])`` — or None once the batcher is closed AND
    drained (the consumer thread's shutdown signal). Thread-safe: any
    number of producers (``put``), one consumer.
    """

    def __init__(self, max_wait_ms: float,
                 bound_for: Callable[[tuple], int],
                 class_weight: Optional[Callable[[int], float]] = None,
                 groups: Optional[List[Any]] = None):
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.bound_for = bound_for
        #: priority-class weight for pop ordering (serve/admission.py's
        #: class_weight_fn in production); None -> all classes equal,
        #: which reproduces the PR 3 discipline exactly
        self.class_weight = class_weight
        #: replica-group queue ids (mesh serving): when set, requests
        #: queue per (group, bucket) and each group's consumer thread
        #: calls ``next_batch(group=...)`` — one engine saturates every
        #: group concurrently. None (the default) is the single
        #: ungrouped pipeline, behavior byte-identical to pre-mesh.
        self.groups = list(groups) if groups else None
        # ordered so the flush scan visits buckets in first-use order —
        # no bucket can be starved behind a constantly-full sibling;
        # grouped mode keys by (group, bucket)
        self._pending: "OrderedDict[tuple, deque]" = OrderedDict()
        #: highest priority currently waiting per queue key (entries
        #: only for nonzero priorities): the weighted full-bucket
        #: election and the priority-pop guard read this in O(1) instead
        #: of scanning the backlog — under overload the consumer thread
        #: must not pay O(total pending) per released batch
        self._maxp: Dict[tuple, int] = {}
        self._cond = threading.Condition()
        self._closed = False
        #: released-batch size histogram {occupied_slots: count} — the
        #: serve report's batch-occupancy evidence
        self.occupancy: Counter = Counter()
        #: per-group occupancy (grouped mode only; the health report's
        #: per-replica-group evidence)
        self.occupancy_by_group: Dict[Any, Counter] = (
            {g: Counter() for g in self.groups} if self.groups else {}
        )

    def _key(self, req: Request) -> tuple:
        if self.groups is None:
            return req.bucket
        if req.group not in self.occupancy_by_group:
            raise ValueError(
                f"request group {req.group!r} not in batcher groups "
                f"{self.groups}"
            )
        return (req.group, req.bucket)

    def _bucket_of(self, key: tuple):
        """The Predictor bucket inside a queue key (grouped keys are
        (group, bucket))."""
        return key[1] if self.groups is not None else key

    def put(self, req: Request) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            key = self._key(req)
            self._pending.setdefault(key, deque()).append(req)
            if req.priority > self._maxp.get(key, 0):
                self._maxp[key] = req.priority
            # grouped mode has one consumer PER group parked on the
            # shared condition: notify_all so the right one wakes
            # (notify() could wake a consumer whose group got nothing)
            if self.groups is None:
                self._cond.notify()
            else:
                self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting; pending requests still drain via next_batch."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _pop(self, key: tuple, n: int) -> Tuple[tuple, List[Request]]:
        bucket = self._bucket_of(key)
        dq = self._pending[key]
        n = min(n, len(dq))
        if self._maxp.get(key, 0):
            # class-weighted pop: release the n highest-priority
            # requests (FIFO within a class). Queues stay arrival-
            # ordered — put() is O(1) and rule 1's oldest-request
            # deadline scan keeps reading dq[0] — so priority is a
            # pop-side SELECTION, not an insertion order. The OLDEST
            # request (dq[0]) always rides: rule 1's max_wait flush
            # fires on ITS age, and leaving it behind for heavier
            # classes would starve low classes indefinitely — priority
            # reorders who ELSE fills the batch, never whether the
            # contractual-maximum waiter finally goes.
            picked = sorted(
                range(1, len(dq)),
                key=lambda i: (-dq[i].priority, dq[i].t_submit),
            )[:n - 1]
            picked_set = {0, *picked}
            out = [dq[i] for i in sorted(picked_set)]
            rest = [r for i, r in enumerate(dq) if i not in picked_set]
            dq.clear()
            dq.extend(rest)
        else:
            out = [dq.popleft() for _ in range(n)]
        if not dq:
            del self._pending[key]
            self._maxp.pop(key, None)
        else:
            if self._maxp.get(key, 0):
                # leftover scan only during priority traffic (the
                # default path never enters this branch)
                mp = max(r.priority for r in dq)
                if mp > 0:
                    self._maxp[key] = mp
                else:
                    self._maxp.pop(key, None)
            # rotate a bucket that released but still holds requests to the
            # back of the scan order: a sustained-load bucket must not
            # monopolize rule 2's full-bucket scan while siblings queue
            self._pending.move_to_end(key)
        self.occupancy[len(out)] += 1
        if self.groups is not None:
            self.occupancy_by_group[key[0]][len(out)] += 1
        if obs.tracing_enabled():
            # queue wait = submit -> release, per request: the window was
            # stamped at submit, so it is recorded retroactively here.
            # Guarded: this runs on the consumer thread OUTSIDE the
            # engine's isolation try blocks — telemetry must never kill
            # the thread that forms batches.
            try:
                now = time.perf_counter()
                for r in out:
                    obs.add_span("serve.queue_wait", r.t_submit, now,
                                 trace_id=r.trace_id or None,
                                 bucket=str(bucket))
            except Exception:
                pass
        return bucket, out

    def next_batch(self, group: Any = None
                   ) -> Optional[Tuple[tuple, List[Request]]]:
        """Block until a batch is due and return ``(bucket, requests)``.

        Grouped mode: each replica group's consumer thread passes its
        ``group`` and sees only that group's queues — the scan/wait
        logic below is per group, so one saturated group never blocks a
        sibling's consumer. Ungrouped (``group=None``, the default
        single-pipeline engine): exactly the original discipline."""
        if (group is None) != (self.groups is None):
            raise ValueError(
                "grouped batchers need next_batch(group=...); ungrouped "
                "ones take none"
            )
        with self._cond:
            while True:
                # 1. an EXPIRED latency deadline releases first — the
                # max_wait_ms bound holds even while a sibling bucket is
                # kept full by sustained load (full buckets can wait one
                # round; an expired lone request has already waited its
                # contractual maximum)
                now = time.perf_counter()
                deadline = None
                due = None
                for key, dq in self._pending.items():
                    if group is not None and key[0] != group:
                        continue
                    t = dq[0].t_submit + self.max_wait_s
                    if deadline is None or t < deadline:
                        deadline, due = t, key
                if deadline is not None and now >= deadline:
                    return self._pop(
                        due,
                        max(1, int(self.bound_for(self._bucket_of(due)))),
                    )
                # 2. any full bucket releases immediately. With a class
                # weighting, the full bucket holding the heaviest-class
                # request wins the slot (ties keep first-use order,
                # rotated by _pop so equals take turns); priority can
                # only reorder WHICH full bucket goes first — rule 1's
                # expired-deadline preemption still bounds every
                # class's wait at max_wait_ms, so no bucket starves.
                best = None
                best_bound = 0
                best_w = 0.0
                for key, dq in self._pending.items():
                    if group is not None and key[0] != group:
                        continue
                    bound = max(
                        1, int(self.bound_for(self._bucket_of(key)))
                    )
                    if len(dq) < bound:
                        continue
                    if self.class_weight is None:
                        return self._pop(key, bound)
                    # O(1) per bucket via the tracked per-bucket max
                    # priority (weights are monotone in class, default
                    # ladder included) — never O(backlog) per release
                    w = self.class_weight(self._maxp.get(key, 0))
                    if best is None or w > best_w:
                        best, best_bound, best_w = key, bound, w
                if best is not None:
                    return self._pop(best, best_bound)
                if self._closed:
                    # drain: flush partial buckets oldest-first
                    for key in self._pending:
                        if group is not None and key[0] != group:
                            continue
                        return self._pop(
                            key,
                            max(1, int(
                                self.bound_for(self._bucket_of(key))
                            )),
                        )
                    return None
                # 3. else sleep until the earliest deadline (or new work)
                self._cond.wait(
                    timeout=None if deadline is None else deadline - now
                )

    def pending(self) -> int:
        with self._cond:
            return sum(len(d) for d in self._pending.values())

    def depth_snapshot(self) -> Dict[tuple, int]:
        """Per-bucket queue depths right now — the health report's
        queue evidence (``ServeEngine.health()``). Grouped batchers
        merge groups per bucket here; :meth:`depth_by_group` carries
        the per-replica-group split."""
        with self._cond:
            out: Dict[tuple, int] = {}
            for key, dq in self._pending.items():
                bucket = self._bucket_of(key)
                out[bucket] = out.get(bucket, 0) + len(dq)
            return out

    def depth_by_group(self) -> Dict[Any, Dict[str, Any]]:
        """Per-replica-group queue depths: ``{group: {"pending": n,
        "per_bucket": {bucket: n}}}`` — the evidence HealthWatch's
        per-group ``queue_saturation`` detector consumes. Empty when
        ungrouped."""
        if self.groups is None:
            return {}
        with self._cond:
            out: Dict[Any, Dict[str, Any]] = {
                g: {"pending": 0, "per_bucket": {}} for g in self.groups
            }
            for (g, bucket), dq in self._pending.items():
                rec = out[g]
                rec["pending"] += len(dq)
                rec["per_bucket"][bucket] = (
                    rec["per_bucket"].get(bucket, 0) + len(dq)
                )
            return out

    def occupancy_snapshot(self, group: Any = None) -> Dict[int, int]:
        with self._cond:
            if group is not None:
                return dict(self.occupancy_by_group.get(group, {}))
            return dict(self.occupancy)
