"""Stream sessions: per-stream temporal feature reuse behind a cheap
host-side delta check.

Video traffic is frame t+1 ≈ frame t almost always; the fused serving
path still pays a full backbone pass per frame. This module opens the
video workload (ROADMAP item 2's temporal half): a
:class:`StreamRouter` in front of one :class:`ServeEngine` keeps one
SESSION per stream id, and each ``submit_stream`` frame takes a cheap
block-mean delta check against the session's anchor frame — the
coarse-stage-elects-expensive-stage pattern applied in time:

- **changed** (delta > ``TMR_STREAM_DELTA``), the session's FIRST
  frame, or reuse disabled: the frame goes through ``engine.submit``
  untouched — bitwise the frame-independent path by construction —
  and becomes the session's new anchor;
- **reused** (delta within threshold): the anchor's backbone features
  come from the router's byte-bounded cache (``TMR_STREAM_CACHE_MB``;
  filled once per anchor — locally, or through the engine's feature
  tier when armed) and the frame submits with ``features=`` — it
  SKIPS the backbone entirely, and its result (cache entry included)
  carries ``degrade_steps: ["temporal_reuse"]`` under its own
  result-cache namespace, so a reused answer can never be served to a
  frame-independent query.

Exactness contract: reuse is OFF by default (``TMR_STREAM_REUSE=0``
disables; the constructor's ``reuse=True`` or ``TMR_STREAM_REUSE=1``
enables), and a frame the delta check calls "changed" is bitwise the
engine's ordinary path. Reuse never crosses stream ids — the feature
cache is keyed by stream id and each session's features derive only
from its own anchor (structural isolation, pinned by
tests/test_streams.py). Sessions idle past ``TMR_STREAM_IDLE_S``
evict lazily on the next submit (counted).

Proof: ``scripts/stream_bench.py`` (one validated ``stream_report/v1``
over a synthetic bursty workload: backbone executions ≪ frames,
≥ 1.5× frames/s over frame-independent, bitwise-exact changed frames).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from tmr_tpu.serve.caches import LRUCache, array_digest

#: block grid of the delta signature: per-block per-channel means on an
#: (at most) GRID×GRID partition of the frame — 192 floats a frame,
#: orders of magnitude cheaper than the backbone it gates
_SIG_GRID = 8


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def block_signature(frame: np.ndarray, grid: int = _SIG_GRID
                    ) -> np.ndarray:
    """The delta check's content signature: per-block per-channel means
    over an (at most) ``grid``×``grid`` partition of the frame.
    Deterministic, host-side, float32 — two bitwise-equal frames have
    bitwise-equal signatures, so an exact-equal frame always reads
    delta 0.0."""
    arr = np.asarray(frame, np.float32)
    g = max(min(int(grid), arr.shape[0], arr.shape[1]), 1)
    rows = []
    for band in np.array_split(arr, g, axis=0):
        for block in np.array_split(band, g, axis=1):
            rows.append(block.reshape(-1, arr.shape[-1]).mean(axis=0))
    return np.stack(rows).astype(np.float32)


class _Session:
    """One stream's state: the anchor frame (the last frame that went
    through the full path), its signature/digest, and the idle clock."""

    __slots__ = ("anchor", "signature", "anchor_digest", "last_active",
                 "frames")

    def __init__(self, anchor: np.ndarray, signature: np.ndarray,
                 anchor_digest: str):
        self.anchor = anchor
        self.signature = signature
        self.anchor_digest = anchor_digest
        self.last_active = time.monotonic()
        self.frames = 0


class StreamRouter:
    """Per-stream temporal feature reuse in front of one ServeEngine
    (module docstring has the contract).

    Parameters
    ----------
    engine: the ServeEngine every frame ultimately submits to.
    reuse: election switch (None -> ``TMR_STREAM_REUSE``, default OFF).
        Off, ``submit_stream`` is a counted passthrough to
        ``engine.submit`` — byte-identical results, no session state.
    delta: block-mean delta threshold (None -> ``TMR_STREAM_DELTA``,
        default 0.02). A frame with delta STRICTLY ABOVE the threshold
        is "changed" (full path, new anchor); at or below reuses — so
        an exact-equal frame (delta 0.0) always reuses and a
        perturbation sized exactly to the threshold still does.
    idle_s: session idle bound (None -> ``TMR_STREAM_IDLE_S``, default
        300): sessions inactive past it evict lazily on the next
        submit (anchor, signature, and cached features all dropped).
    cache_mb: byte bound on the anchor-feature cache (None ->
        ``TMR_STREAM_CACHE_MB``, default 64) — streams beyond the
        bound just refill on their next reused frame.
    """

    def __init__(self, engine, *, reuse: Optional[bool] = None,
                 delta: Optional[float] = None,
                 idle_s: Optional[float] = None,
                 cache_mb: Optional[float] = None):
        self._engine = engine
        self.reuse = (
            _env_int("TMR_STREAM_REUSE", 0) != 0
            if reuse is None else bool(reuse)
        )
        self.delta = (
            _env_float("TMR_STREAM_DELTA", 0.02)
            if delta is None else float(delta)
        )
        self.idle_s = (
            _env_float("TMR_STREAM_IDLE_S", 300.0)
            if idle_s is None else float(idle_s)
        )
        mb = (
            _env_float("TMR_STREAM_CACHE_MB", 64.0)
            if cache_mb is None else float(cache_mb)
        )
        self._lock = threading.RLock()
        self._sessions: Dict[str, _Session] = {}
        #: anchor features keyed by STREAM ID (value carries the anchor
        #: digest it derives from): reuse structurally cannot cross
        #: streams — there is no key under which stream A could read
        #: stream B's features
        self._features = LRUCache(
            4096, registry=engine.metrics, name="stream.cache.feature",
            max_bytes=int(mb * (1 << 20)) if mb > 0 else None,
        )
        #: lazily created ``stream.*`` counters on the ENGINE's
        #: registry (the engine._mx pattern): snapshots of an engine
        #: that never saw stream traffic stay byte-identical
        self._mx: Dict[str, Any] = {}

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            c = self._mx.get(name)
            if c is None:
                c = self._mx[name] = self._engine.metrics.counter(
                    f"stream.{name}"
                )
        c.inc(n)

    # -------------------------------------------------------------- submit
    def submit_stream(self, stream_id: str, frame, exemplars,
                      priority: int = 0,
                      deadline_ms: Optional[float] = None) -> Future:
        """Submit one frame of one stream; returns the engine Future.
        Single-exemplar only (temporal reuse rides the heads-only
        program, which has no multi-exemplar formulation)."""
        sid = str(stream_id)
        self._count("frames")
        if not self.reuse:
            # disabled (the default): a pure counted passthrough —
            # byte-identical to frame-independent submission
            return self._engine.submit(frame, exemplars,
                                       priority=priority,
                                       deadline_ms=deadline_ms)
        arr = np.asarray(frame, np.float32)
        if arr.ndim == 4 and arr.shape[0] == 1:
            arr = arr[0]
        self._sweep_idle()
        sig = block_signature(arr)
        features = None
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                verdict = "first"
            else:
                d = float(np.max(np.abs(sig - sess.signature)))
                verdict = "changed" if d > self.delta else "reused"
            if verdict == "reused":
                sess.last_active = time.monotonic()
                sess.frames += 1
                anchor = sess.anchor
                anchor_digest = sess.anchor_digest
            else:
                # full path: this frame becomes the session's anchor
                # and any cached features for the OLD anchor drop
                anchor = np.ascontiguousarray(arr)
                anchor_digest = array_digest(anchor)
                fresh = _Session(anchor, sig, anchor_digest)
                if sess is not None:
                    fresh.frames = sess.frames + 1
                self._sessions[sid] = fresh
                self._features.pop((sid,))
        if verdict != "reused":
            self._count("first_frames" if verdict == "first"
                        else "changed_frames")
            return self._engine.submit(arr, exemplars,
                                       priority=priority,
                                       deadline_ms=deadline_ms)
        features = self._anchor_features(sid, anchor, anchor_digest)
        self._count("reused_frames")
        return self._engine.submit(arr, exemplars, priority=priority,
                                   deadline_ms=deadline_ms,
                                   features=features)

    def _anchor_features(self, sid: str, anchor: np.ndarray,
                         anchor_digest: str) -> np.ndarray:
        """The anchor's backbone features, filled ONCE per anchor into
        the byte-bounded cache: through the engine's feature tier when
        armed and holding (counted ``remote_fills``), else one local
        backbone call (``local_fills``). The device call happens
        OUTSIDE the router lock; a racing duplicate fill computes the
        same value twice — benign."""
        with self._lock:
            entry = self._features.get((sid,))
        if entry is not None and entry[0] == anchor_digest:
            return entry[1]
        size = int(anchor.shape[0])
        feats = None
        client = getattr(self._engine, "_feature_client", None)
        if client is not None:
            try:
                feats = client.fetch(anchor, anchor_digest, size)
            except Exception:
                feats = None
            if feats is not None:
                self._count("remote_fills")
        if feats is None:
            pred = self._engine._pred
            bb = pred._get_backbone_fn()
            exec_params = getattr(pred, "exec_params", None)
            params = exec_params() if callable(exec_params) \
                else pred.params
            feats = bb(params, anchor[None])
            self._count("local_fills")
        feats = np.asarray(feats)  # host copy: cached bytes accountable
        with self._lock:
            self._features.put((sid,), (anchor_digest, feats))
        return feats

    # ----------------------------------------------------------- lifecycle
    def _sweep_idle(self) -> None:
        """Lazy idle eviction (no background thread to lock-discipline):
        every submit drops sessions inactive past ``idle_s``."""
        if self.idle_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            dead = [sid for sid, s in self._sessions.items()
                    if now - s.last_active > self.idle_s]
            for sid in dead:
                del self._sessions[sid]
                self._features.pop((sid,))
        if dead:
            self._count("evicted_sessions", len(dead))

    def evict(self, stream_id: str) -> bool:
        """Drop one session (and its cached features) now; True when it
        existed."""
        sid = str(stream_id)
        with self._lock:
            existed = self._sessions.pop(sid, None) is not None
            self._features.pop((sid,))
        if existed:
            self._count("evicted_sessions")
        return existed

    def sessions(self) -> Dict[str, dict]:
        with self._lock:
            return {
                sid: {"frames": s.frames,
                      "idle_s": round(
                          time.monotonic() - s.last_active, 3
                      ),
                      "anchor_digest": s.anchor_digest}
                for sid, s in self._sessions.items()
            }

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {name: int(c.value) for name, c in self._mx.items()}

    def stats(self) -> dict:
        with self._lock:
            n = len(self._sessions)
        return {
            "reuse": self.reuse,
            "delta": self.delta,
            "idle_s": self.idle_s,
            "sessions": n,
            "feature_cache": self._features.stats(),
            **self.counters(),
        }
