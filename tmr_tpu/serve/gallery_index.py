"""Coarse-to-fine sketch index over gallery entries (ISSUE 18).

The linear prefilter scores every registered entry's Rademacher sketch
against the frame — O(N) device work per frame, hopeless at catalog
scale. This module holds the host-side half of the sublinear
replacement: a two-level IVF-style index.

The key observation making an IVF index *principled* here: the coarse
prefilter score (``ops.xcorr.coarse_prefilter_scores``) is a function
of (frame features, exemplar box geometry) ONLY — the template is
extracted from the frame's own feature map at the entry's box
coordinates. Entries with identical boxes score identically on every
frame, and nearby boxes score nearby (the sketch correlation is
continuous in the crop window). So clustering entries by an 8-dim
box-geometry vector groups entries whose sketch scores co-move, and a
couple of real member entries per cluster are a faithful probe: the
*medoid* (nearest the centroid) plus the *anti-medoid* (farthest —
the boundary sample that catches a cluster whose best scorer is an
outlier). Score all ~2*sqrt(N) probes on-device in one batched call,
rank buckets by their probes' MAX, take the best ``nprobe`` buckets,
and run the exact sketch correlation only over their members.

Determinism contract (the fleet promotes replicas and rebuilds from
journals — a promoted shard must elect the same candidates as the
primary it replaced): k-means runs over NAME-SORTED entries with a
pinned seed and a fixed Lloyd iteration count, empty clusters reseed
deterministically, and medoid ties break toward the lexicographically
smallest name. Same entry set in => byte-identical clustering out,
regardless of registration order.

Maintenance is incremental: register/evict assign/unassign against the
built clustering and bump a churn counter; past
``rebuild_frac * built_n`` churn the owner triggers ``rebuild()``,
which returns a journaled *stamp* (entries, centroids, wall seconds,
entry-set digest) kept in a bounded on-index log.

Everything here is host-side numpy — device scoring stays in
``GalleryBank`` (serve/gallery.py), which owns the knobs
(``TMR_GALLERY_INDEX*``) and the fallback-to-linear contract.
"""

import hashlib
import math
import threading
import time
from typing import Dict, List, Optional

import numpy as np

# dims of the per-entry geometry vector: mean/std of box centers and
# extents over the entry's real exemplar rows
SKETCH_DIMS = 8

# pinned k-means seed — part of the cross-replica determinism contract
DEFAULT_SEED = 20260807

_LLOYD_ITERS = 8
_DIST_CHUNK = 8192


def entry_sketch(exemplars, k_real) -> np.ndarray:
    """The (SKETCH_DIMS,) float32 geometry vector for one entry.

    ``exemplars`` is the (possibly padded) (K, 4) normalized-xyxy box
    array; only the first ``k_real`` rows are real. The vector captures
    where the entry's crops sit on the frame (centers) and how big they
    are (extents) — exactly the quantities the coarse sketch score
    depends on.
    """
    ex = np.asarray(exemplars, np.float32).reshape(-1, 4)
    k = max(int(k_real), 1)
    ex = ex[: min(k, ex.shape[0])]
    cx = (ex[:, 0] + ex[:, 2]) * 0.5
    cy = (ex[:, 1] + ex[:, 3]) * 0.5
    w = ex[:, 2] - ex[:, 0]
    h = ex[:, 3] - ex[:, 1]
    return np.asarray(
        [cx.mean(), cy.mean(), w.mean(), h.mean(),
         cx.std(), cy.std(), w.std(), h.std()],
        np.float32,
    )


def _pairwise_d2(x: np.ndarray, cent: np.ndarray) -> np.ndarray:
    """Squared L2 distances (n, C), chunked so a 10^5-entry rebuild
    never materializes more than ``_DIST_CHUNK * C`` floats at once."""
    out = np.empty((x.shape[0], cent.shape[0]), np.float32)
    cn = (cent * cent).sum(axis=1)
    for lo in range(0, x.shape[0], _DIST_CHUNK):
        xs = x[lo:lo + _DIST_CHUNK]
        out[lo:lo + xs.shape[0]] = (
            (xs * xs).sum(axis=1)[:, None] - 2.0 * (xs @ cent.T) + cn[None, :]
        )
    return out


def _kmeans(x: np.ndarray, n_clusters: int, seed: int):
    """Deterministic Lloyd k-means: pinned-seed init over the (already
    name-sorted) rows, fixed iteration count, empty clusters reseeded
    to the globally worst-fit point (lowest index on ties via argmax).
    Returns (centroids (C, D), assignment (n,))."""
    n = x.shape[0]
    n_clusters = max(1, min(int(n_clusters), n))
    rng = np.random.default_rng(seed)
    pick = np.sort(rng.permutation(n)[:n_clusters])
    cent = x[pick].astype(np.float32).copy()
    assign = np.zeros((n,), np.int64)
    for _ in range(_LLOYD_ITERS):
        d2 = _pairwise_d2(x, cent)
        assign = d2.argmin(axis=1)
        own = d2[np.arange(n), assign]
        for c in range(n_clusters):
            mask = assign == c
            if mask.any():
                cent[c] = x[mask].mean(axis=0)
            else:
                far = int(own.argmax())
                cent[c] = x[far]
                assign[far] = c
                own[far] = 0.0
    return cent, assign


class SketchIndex:
    """Two-level IVF index over entry geometry sketches (host side).

    Thread-safe: every public method takes the index lock; callers
    (GalleryBank under its own lock, the fleet worker's bank) may share
    one instance freely.
    """

    def __init__(self, *, seed: int = DEFAULT_SEED,
                 rebuild_frac: float = 0.25, min_centroids: int = 1,
                 max_stamps: int = 64):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._rebuild_frac = float(rebuild_frac)
        self._min_centroids = max(int(min_centroids), 1)
        self._max_stamps = max(int(max_stamps), 1)
        self._vectors: Dict[str, np.ndarray] = {}
        self._centroids: Optional[np.ndarray] = None
        self._medoids: List[Optional[str]] = []
        self._antis: List[Optional[str]] = []
        self._members: List[List[str]] = []
        self._assign: Dict[str, int] = {}
        self._churn = 0
        self._built_n = 0
        self._rebuilds = 0
        self._stamps: List[dict] = []

    def __len__(self) -> int:
        with self._lock:
            return len(self._vectors)

    @property
    def built(self) -> bool:
        with self._lock:
            return self._centroids is not None

    def add(self, name: str, vector: np.ndarray) -> None:
        """Register (or re-register) one entry's sketch vector. Against
        a built clustering the entry is assigned to its nearest
        centroid immediately — queries see it before any rebuild."""
        name = str(name)
        v = np.asarray(vector, np.float32).reshape(-1)
        with self._lock:
            self._unassign_locked(name)
            self._vectors[name] = v
            if self._centroids is not None:
                d2 = ((self._centroids - v[None, :]) ** 2).sum(axis=1)
                ci = int(d2.argmin())
                self._assign[name] = ci
                self._members[ci].append(name)
                # probes stay EXACT extrema over the member set (not
                # merely updated-if-better): order-independent, so an
                # incrementally maintained index and a fresh rebuild
                # over the same entries elect the same probes
                self._medoids[ci] = self._pick_medoid_locked(ci)
                self._antis[ci] = self._pick_anti_locked(ci)
            self._churn += 1

    def remove(self, name: str) -> bool:
        """Drop one entry. Returns True if it was indexed. Evicted
        entries leave the posting lists immediately, so a stale-but-
        built index can never hand an evicted name back to a query."""
        name = str(name)
        with self._lock:
            if name not in self._vectors:
                return False
            self._unassign_locked(name)
            del self._vectors[name]
            self._churn += 1
            return True

    def _unassign_locked(self, name: str) -> None:
        ci = self._assign.pop(name, None)
        if ci is None:
            return
        try:
            self._members[ci].remove(name)
        except ValueError:
            pass
        if self._medoids[ci] == name:
            self._medoids[ci] = self._pick_medoid_locked(ci)
        if self._antis[ci] == name:
            self._antis[ci] = self._pick_anti_locked(ci)

    def _pick_medoid_locked(self, ci: int) -> Optional[str]:
        members = self._members[ci]
        if not members:
            return None
        cent = self._centroids[ci]
        return min(
            members,
            key=lambda nm: (
                float(((self._vectors[nm] - cent) ** 2).sum()), nm),
        )

    def _pick_anti_locked(self, ci: int) -> Optional[str]:
        """The boundary probe: the member FARTHEST from the centroid
        (ties toward the lexicographically largest name — any fixed
        rule keeps replicas byte-identical)."""
        members = self._members[ci]
        if not members:
            return None
        cent = self._centroids[ci]
        return max(
            members,
            key=lambda nm: (
                float(((self._vectors[nm] - cent) ** 2).sum()), nm),
        )

    def needs_rebuild(self) -> bool:
        """True when the index has never been built, or incremental
        churn since the last build exceeds ``rebuild_frac`` of the
        built entry count."""
        with self._lock:
            if not self._vectors:
                return False
            if self._centroids is None:
                return True
            return self._churn > max(1.0,
                                     self._rebuild_frac * self._built_n)

    def rebuild(self, reason: str = "churn") -> dict:
        """Recluster from scratch (deterministic — see module doc) and
        return the journaled rebuild stamp."""
        t0 = time.perf_counter()
        with self._lock:
            names = sorted(self._vectors)
            n = len(names)
            if n == 0:
                self._centroids = None
                self._medoids, self._members, self._assign = [], [], {}
                self._antis = []
                self._built_n, self._churn = 0, 0
                stamp = self._stamp_locked(reason, 0, 0, t0, names)
                return stamp
            x = np.stack([self._vectors[nm] for nm in names])
            n_clusters = max(self._min_centroids,
                             int(round(math.sqrt(float(n)))))
            cent, assign = _kmeans(x, n_clusters, self._seed)
            members: List[List[str]] = [[] for _ in range(cent.shape[0])]
            for i, nm in enumerate(names):
                members[int(assign[i])].append(nm)
            self._centroids = cent
            self._members = members
            self._assign = {nm: int(assign[i]) for i, nm in enumerate(names)}
            self._medoids = [self._pick_medoid_locked(ci)
                             for ci in range(cent.shape[0])]
            self._antis = [self._pick_anti_locked(ci)
                           for ci in range(cent.shape[0])]
            self._built_n = n
            self._churn = 0
            stamp = self._stamp_locked(reason, n, int(cent.shape[0]), t0,
                                       names)
            return stamp

    def _stamp_locked(self, reason: str, entries: int, centroids: int,
                      t0: float, names: List[str]) -> dict:
        self._rebuilds += 1
        digest = hashlib.sha256(
            ("|".join(names) + f"|seed={self._seed}|c={centroids}").encode()
        ).hexdigest()[:16]
        stamp = {
            "rebuild": self._rebuilds,
            "reason": str(reason),
            "entries": int(entries),
            "centroids": int(centroids),
            "wall_s": round(time.perf_counter() - t0, 6),
            "digest": digest,
        }
        self._stamps.append(stamp)
        if len(self._stamps) > self._max_stamps:
            del self._stamps[: len(self._stamps) - self._max_stamps]
        return dict(stamp)

    def snapshot(self) -> dict:
        """A query-time view: parallel ``medoids`` / ``probes`` /
        ``members`` lists for every non-empty cluster (``probes[i]`` is
        the medoid plus the anti-medoid when distinct — a bucket is
        ranked by its probes' MAX score). Safe to use outside the lock
        — the inner lists are copies."""
        with self._lock:
            if self._centroids is None:
                return {"built": False, "medoids": [], "probes": [],
                        "members": [], "centroids": 0}
            meds, probes, mems = [], [], []
            for ci, medoid in enumerate(self._medoids):
                if medoid is not None and self._members[ci]:
                    meds.append(medoid)
                    anti = self._antis[ci]
                    probes.append(
                        [medoid] if anti in (None, medoid)
                        else [medoid, anti]
                    )
                    mems.append(list(self._members[ci]))
            return {"built": True, "medoids": meds, "probes": probes,
                    "members": mems, "centroids": len(meds)}

    def stats(self) -> dict:
        with self._lock:
            return {
                "built": self._centroids is not None,
                "entries": len(self._vectors),
                "centroids": (0 if self._centroids is None
                              else int(self._centroids.shape[0])),
                "built_n": self._built_n,
                "churn": self._churn,
                "rebuilds": self._rebuilds,
                "rebuild_frac": self._rebuild_frac,
                "seed": self._seed,
                "last_rebuild": (dict(self._stamps[-1])
                                 if self._stamps else None),
            }

    def stamps(self) -> List[dict]:
        """The bounded journal of rebuild stamps, oldest first."""
        with self._lock:
            return [dict(s) for s in self._stamps]
