"""Replicated gallery partitions on the lease service — pattern shards
as leased fleet resources with crash-proof registration.

PR 15's :class:`~tmr_tpu.serve.gallery.GalleryBank` is one process's
device memory: a ``kill -9`` of the bank holder silently loses every
registered pattern. This module makes gallery state a cluster resource
(ROADMAP item 1's sharded-bank half):

- **pattern shards** (``name -> stable hash % n_shards``) are leased
  from the same :class:`~tmr_tpu.parallel.leases.LeaseService` state
  machine the map/serve/feature fleets use — :class:`GalleryFleet` is
  the coordinator (hello/lease/beat/bye over the fleet control
  protocol, liveness via ``expire_pass``);
- **registration is durable BEFORE it is acknowledged**: every
  ``register`` first appends to a write-ahead :class:`PatternJournal`
  (the ``parallel/journal.py`` discipline — atomic marker + payload
  digest + an optional fence that aborts marker-less), then pushes the
  payload to the shard's primary AND mirrors it to R−1 replicas
  (``TMR_GALLERY_REPLICAS``), acking with the replica count. Worker
  death between register and search loses nothing: the journal and the
  surviving copies re-materialize the shard on promotion;
- **promotion re-materializes**: when a lease rebalances onto a new
  holder the coordinator sends ``adopt`` (install from the worker's
  local replica store, digest-checked) and pushes any missing payloads
  from its catalog, then re-mirrors so replication heals back to R;
- the **front door** is :class:`GalleryFleetClient`: one frame fans
  out to the workers holding its shards and the disjoint per-shard
  results union (per-entry NMS already ran worker-side, exactly as in
  the single bank — healthy-fleet fan-out is byte-identical to one
  bank holding every pattern). A dead/slow/fenced shard degrades to
  empty detections carrying ``degrade_steps:
  ["partition_unavailable"]`` — a counted partial result, never an
  error — and heals when the lease rebalances onto a replica.

Fault points (``tmr_tpu/utils/faults.py`` closed vocabulary):
``serve.link`` fires before each fan-out write (a raise severs the
link), ``gallery.replica`` fires/corrupts each replica push (a
digest-checked worker rejects the corrupt copy and the push retries),
``gallery.beat`` fires before each worker heartbeat (``latency=S``
past the TTL is the SIGSTOP stand-in — the shard goes stale and is
promoted onto a replica). ``scripts/serve_chaos_probe.py`` drives all
of it and emits a validated ``serve_chaos_report/v1``.

Everything here is OFF by default: nothing imports this module unless
a fleet is constructed, and the single-bank path is untouched.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tmr_tpu.obs import fleetobs as _fleetobs
from tmr_tpu.parallel.journal import StaleLeaseError  # noqa: F401 — re-export
from tmr_tpu.parallel.leases import (
    LeasePolicy,
    LeaseService,
    Resource,
    connect_timeout,
    oneshot,
    recv_line,
    send_line,
)
from tmr_tpu.serve.feature_tier import _ExtractLink
from tmr_tpu.serve.fleet import fleet_policy, pack_array, unpack_array
from tmr_tpu.serve.gallery import FeatureSinkServer
from tmr_tpu.utils import faults
from tmr_tpu.utils.atomicio import atomic_write


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def shard_of(name: str, n_shards: int) -> int:
    """Stable pattern->shard placement: a sha256 prefix, NOT ``hash()``
    (randomized per process — coordinator restarts must re-derive the
    same placement the journal recorded)."""
    h = hashlib.sha256(str(name).encode()).hexdigest()[:8]
    return int(h, 16) % max(int(n_shards), 1)


# ------------------------------------------------------------- partitions
class PatternShard(Resource):
    """One gallery pattern shard. Leased for the lifetime of its
    holder (never settles)."""

    __slots__ = ()

    def __init__(self, index: int):
        super().__init__(index, f"gshard{index}")


# ---------------------------------------------------------------- journal
#: schema tag stamped on every pattern marker — bump on incompatible change
GALLERY_JOURNAL_SCHEMA = "gallery_journal/v1"

#: payload fields covered by the marker digest (order matters — it is
#: the canonical serialization the digest is computed over)
_MARKER_FIELDS = ("name", "shard", "k_real", "payload")


def _marker_digest(entry: dict) -> str:
    blob = json.dumps(
        [entry.get(k) for k in _MARKER_FIELDS], sort_keys=True
    ).encode()
    return hashlib.sha256(blob).hexdigest()


class PatternJournal:
    """Write-ahead journal of pattern registrations — one atomic JSON
    marker per pattern (the ``parallel/journal.py`` discipline:
    tmp + ``os.replace``, a digest over the payload fields, and an
    optional ``fence`` callable invoked right before the write whose
    raise — :class:`StaleLeaseError` — aborts the commit marker-less).
    A registration is acknowledged only after its marker is durable,
    so a crash anywhere downstream re-materializes from here."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name: str) -> str:
        stem = re.sub(r"[^\w.-]", "_", str(name)) or "_unnamed"
        suffix = hashlib.sha256(str(name).encode()).hexdigest()[:8]
        return os.path.join(self.directory, f"{stem}-{suffix}.json")

    def record(self, name: str, shard: int, payload: dict, k_real: int,
               fence: Optional[Callable[[], None]] = None) -> dict:
        """Atomically commit one pattern marker. The ``journal`` fault
        point fires before anything touches disk; ``fence`` (when
        given) runs after it and before the write — raising aborts the
        commit with NO marker written."""
        faults.fire("journal")
        if fence is not None:
            fence()
        entry = {
            "schema": GALLERY_JOURNAL_SCHEMA,
            "name": str(name),
            "shard": int(shard),
            "k_real": int(k_real),
            "payload": {
                "b64": payload["b64"],
                "dtype": payload["dtype"],
                "shape": list(payload["shape"]),
            },
        }
        entry["digest"] = _marker_digest(entry)
        atomic_write(self._path(name), lambda f: json.dump(entry, f))
        return entry

    def invalidate(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def load_all(self) -> Dict[str, dict]:
        """Every valid marker keyed by pattern name; truncated or
        hand-edited markers fail the digest check and are skipped (the
        pattern was never acknowledged durable)."""
        out: Dict[str, dict] = {}
        for fn in sorted(os.listdir(self.directory)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.directory, fn)) as f:
                    entry = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(entry, dict):
                continue
            if entry.get("schema") != GALLERY_JOURNAL_SCHEMA:
                continue
            if entry.get("digest") != _marker_digest(entry):
                continue
            out[entry["name"]] = entry
        return out


# ----------------------------------------------------------- wire helpers
def _payload_digest(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()


def pack_results(results: Dict[str, dict]) -> Dict[str, dict]:
    """Pack one ``{name: dets}`` search result for the wire: arrays
    b64-exact (fan-out stays bitwise vs the local bank), non-array
    fields (``degrade_steps``, ``prefilter_score``) as plain JSON."""
    out: Dict[str, dict] = {}
    for name, dets in results.items():
        arrays: Dict[str, dict] = {}
        extra: Dict[str, Any] = {}
        for key, val in dets.items():
            if isinstance(val, np.ndarray):
                arrays[key] = pack_array(val)
            else:
                extra[key] = val
        out[name] = {"arrays": arrays, "extra": extra}
    return out


def unpack_results(doc: Dict[str, dict]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for name, rec in doc.items():
        dets = {
            key: unpack_array(val)
            for key, val in (rec.get("arrays") or {}).items()
        }
        dets.update(rec.get("extra") or {})
        out[name] = dets
    return out


def unavailable_result() -> dict:
    """The degraded per-pattern result for a dead/slow/fenced shard:
    the single bank's empty-detections shape with the partition label
    — a counted partial result, never an error."""
    return {
        "boxes": np.zeros((1, 0, 4), np.float32),
        "scores": np.zeros((1, 0), np.float32),
        "refs": np.zeros((1, 0, 2), np.float32),
        "valid": np.zeros((1, 0), bool),
        "degrade_steps": ["partition_unavailable"],
    }


# ------------------------------------------------------------ coordinator
class _GalleryHandler(socketserver.StreamRequestHandler):
    """Control-plane handler (the fleet _FleetHandler shape): JSON
    lines in/out; EOF with leases held is the kill -9 signature."""

    def handle(self):  # noqa: D102 — protocol loop
        fleet = self.server.fleet  # type: ignore[attr-defined]
        control_worker = None
        clean = False
        try:
            while True:
                try:
                    msg = recv_line(self.rfile)
                except (OSError, ValueError):
                    break
                if msg is None:
                    break
                if msg.get("op") == "hello":
                    control_worker = msg.get("worker")
                if msg.get("op") == "bye":
                    clean = True
                reply = fleet.dispatch(msg)
                try:
                    send_line(self.connection, reply)
                except OSError:
                    break
                if clean:
                    break
        finally:
            if control_worker is not None:
                fleet.control_closed(control_worker, clean=clean)


class _GalleryServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class GalleryFleet:
    """The gallery-fleet coordinator: workers lease pattern shards
    here; the catalog (pattern name -> shard, payload, copies) lives
    here, backed by the write-ahead :class:`PatternJournal`. One per
    cluster, usually co-located with the front door."""

    def __init__(self, n_shards: int, *,
                 policy: Optional[LeasePolicy] = None,
                 replicas: Optional[int] = None,
                 journal_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 check_interval_s: Optional[float] = None,
                 push_timeout_s: Optional[float] = None):
        self.n_shards = int(n_shards)
        if self.n_shards < 1:
            raise ValueError("a gallery fleet needs at least one shard")
        #: total copies per pattern (primary + mirrors) the fleet tries
        #: to keep on LIVE workers; fewer live workers than R is
        #: counted under-replication, never an error
        self.replicas = max(
            _env_int("TMR_GALLERY_REPLICAS", 2)
            if replicas is None else int(replicas), 1,
        )
        shards = [PatternShard(i) for i in range(self.n_shards)]
        self.policy = fleet_policy(policy)
        self._svc = LeaseService(
            shards, self.policy,
            metrics_prefix="gallery_fleet", noun="partition",
            key_field="partition",
            history_bound=4096,
        )
        self._shards = shards
        self._host, self._port = host, int(port)
        self._lock = threading.RLock()
        self._worker_addr: Dict[str, Tuple[str, int]] = {}
        #: pattern name -> {name, shard, k_real, payload, digest,
        #: copies: set(worker id)} — insertion-ordered (registration
        #: order, like the single bank's entries)
        self._patterns: Dict[str, dict] = {}
        self._counters: Dict[str, int] = {
            "registrations": 0, "evictions": 0, "journal_recovered": 0,
            "replica_pushes": 0, "replica_corrupt": 0,
            "push_failures": 0, "under_replicated": 0,
            "promotions": 0, "adopt_installed": 0, "adopt_pushed": 0,
            "materialize_errors": 0, "bulk_registered": 0,
            "bulk_flushes": 0,
        }
        #: per-worker bank stats from the last heartbeat (entry counts
        #: + sketch-index state per held shard) — the fleet's window
        #: into each shard's index health without a gstate round-trip
        self._beat_banks: Dict[str, dict] = {}
        #: the streamed bulk-ingest sink (started on demand)
        self._bulk: Optional[FeatureSinkServer] = None
        self._journal = (
            PatternJournal(journal_dir) if journal_dir else None
        )
        if self._journal is not None:
            # coordinator restart: the WAL is the catalog of record —
            # every durable (acknowledged) registration survives here
            for name, entry in self._journal.load_all().items():
                self._patterns[name] = {
                    "name": name,
                    "shard": int(entry["shard"]),
                    "k_real": int(entry["k_real"]),
                    "payload": dict(entry["payload"]),
                    "digest": _payload_digest(
                        base64.b64decode(entry["payload"]["b64"])
                    ),
                    "copies": set(),
                }
                self._counters["journal_recovered"] += 1
        self._push_timeout = (
            _env_float("TMR_GALLERY_FLEET_TIMEOUT_S", 10.0)
            if push_timeout_s is None else float(push_timeout_s)
        )
        self._closed = False
        self._stop_event = threading.Event()
        self._server: Optional[_GalleryServer] = None
        self._threads: List[threading.Thread] = []
        self._check_s = (
            self.policy.check_interval_s
            if check_interval_s is None else float(check_interval_s)
        )

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        server = _GalleryServer((self._host, self._port), _GalleryHandler)
        server.fleet = self  # type: ignore[attr-defined]
        threads = [
            threading.Thread(target=server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             name="gallery-fleet-control", daemon=True),
            threading.Thread(target=self._monitor_loop,
                             name="gallery-fleet-monitor", daemon=True),
        ]
        with self._lock:
            self._server = server
            self._threads = threads
        self._svc.restart_clock()
        for t in threads:
            t.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            assert self._server is not None, "gallery fleet not started"
            return self._server.server_address[:2]

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            server = self._server
            threads = list(self._threads)
            bulk, self._bulk = self._bulk, None
        self._stop_event.set()
        if bulk is not None:
            bulk.close()
        if server is not None:
            server.shutdown()
            server.server_close()
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))

    def __enter__(self) -> "GalleryFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(self._check_s):
            try:
                self._svc.expire_pass()
            except Exception:
                pass  # the liveness loop must survive anything

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    # ----------------------------------------------------- control protocol
    def dispatch(self, msg: dict) -> dict:
        op = msg.get("op")
        handler = {
            "hello": self._op_hello,
            "lease": self._op_lease,
            "beat": self._op_beat,
            "fail": self._op_fail,
            "bye": self._op_bye,
            "state": lambda m: self.state(),
        }.get(op)
        if handler is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            return handler(msg)
        except Exception as e:  # protocol must answer, never wedge
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _op_hello(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        self._svc.rejoin(wid)
        data_addr = msg.get("data_addr")
        if isinstance(data_addr, (list, tuple)) and len(data_addr) == 2:
            with self._lock:
                self._worker_addr[wid] = (str(data_addr[0]),
                                          int(data_addr[1]))
        return {
            "ok": True,
            "shards": self.n_shards,
            "replicas": self.replicas,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
        }

    def _op_lease(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        wait = {"partition": None,
                "wait_s": max(self.policy.check_interval_s, 0.05)}
        verdict, part, epoch = self._svc.select(wid)
        if verdict == "drained":
            return {"partition": None, "drained": True}
        if verdict != "grant":
            return wait  # fleets are never "done" while serving
        if self._svc.install(part, epoch, wid) is None:
            return wait
        # promotion re-materialization happens BEFORE the grant
        # returns: by the time the worker records the lease, its bank
        # holds every durable pattern of the shard (replica store
        # first, catalog push for the rest) — searches that raced the
        # rebalance were fenced, searches after the grant are whole
        self._materialize(part, epoch, wid)
        return {
            "partition": part.key,
            "index": part.index,
            "epoch": epoch,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
        }

    def _op_beat(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        stale: List[List[int]] = []
        for pair in msg.get("held") or ():
            index, epoch = int(pair[0]), int(pair[1])
            if not self._svc.heartbeat(wid, index, epoch):
                stale.append([index, epoch])
        banks = msg.get("banks")
        if isinstance(banks, dict):
            with self._lock:
                self._beat_banks[wid] = banks
        worker = self._svc.worker_rec(wid)
        return {"ok": True, "stale": stale, "drained": worker.drained}

    def _op_fail(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        res = self._svc.fail(wid, index, epoch, msg.get("causes") or [])
        return {"ok": True, **res}

    def _op_bye(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        self._svc.bye(wid)
        self._svc.revoke_worker(wid, "worker_exit")
        return {"ok": True}

    def control_closed(self, wid: str, clean: bool) -> None:
        self._svc.control_closed(str(wid), clean)

    # ------------------------------------------------------------ placement
    def shard_of(self, name: str) -> int:
        return shard_of(name, self.n_shards)

    def holder_for(self, shard: int
                   ) -> Optional[Tuple[str, int, Tuple[str, int]]]:
        """The live holder of one shard as ``(worker id, epoch, data
        address)`` — or None (unheld, or a holder that never registered
        a data plane)."""
        holder = self._svc.holder(int(shard))
        if holder is None:
            return None
        wid, epoch = holder
        with self._lock:
            addr = self._worker_addr.get(wid)
        if addr is None:
            return None
        return wid, epoch, addr

    def _addr_of(self, wid: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._worker_addr.get(wid)

    def shard_map(self) -> Dict[int, List[str]]:
        """Registered pattern names per shard, registration order —
        the front door's fan-out plan."""
        out: Dict[int, List[str]] = {}
        with self._lock:
            for name, entry in self._patterns.items():
                out.setdefault(entry["shard"], []).append(name)
        return out

    def patterns(self) -> List[str]:
        with self._lock:
            return list(self._patterns)

    # ----------------------------------------------------------- registrar
    def register(self, name: str, exemplars, k_real: Optional[int] = None
                 ) -> dict:
        """Durably register one pattern. Ordering is the correctness
        contract: journal FIRST (the write-ahead marker), catalog,
        then primary push + replica mirrors — the ack carries how many
        copies acknowledged, and a crash at ANY later point loses
        nothing because the marker already vouches."""
        name = str(name)
        arr = np.ascontiguousarray(np.asarray(exemplars, np.float32))
        kr = int(k_real) if k_real is not None else int(
            arr.shape[0] if arr.ndim >= 1 else 1
        )
        shard = self.shard_of(name)
        payload = pack_array(arr)
        entry = {
            "name": name,
            "shard": shard,
            "k_real": kr,
            "payload": payload,
            "digest": _payload_digest(arr.tobytes()),
            "copies": set(),
        }
        if self._journal is not None:
            self._journal.record(name, shard, payload, kr)
        with self._lock:
            self._patterns[name] = entry
            self._counters["registrations"] += 1
        copies = self._distribute(entry)
        under = copies < min(self.replicas,
                             max(len(self._svc.live_workers()), 1))
        if under:
            self._count("under_replicated")
        return {
            "ok": True,
            "name": name,
            "shard": shard,
            "copies": copies,
            "journaled": self._journal is not None,
            "under_replicated": under,
        }

    def evict(self, name: str) -> bool:
        name = str(name)
        if self._journal is not None:
            self._journal.invalidate(name)
        with self._lock:
            entry = self._patterns.pop(name, None)
            if entry is None:
                return False
            self._counters["evictions"] += 1
            copies = set(entry["copies"])
        for wid in copies:
            addr = self._addr_of(wid)
            if addr is None:
                continue
            try:
                oneshot(addr, {"op": "evict_pattern", "name": name,
                               "shard": entry["shard"]},
                        timeout=self._push_timeout)
            except Exception:
                pass  # a dead copy-holder has nothing left to evict
        return True

    # --------------------------------------------------------- bulk ingest
    def bulk_sink(self) -> Tuple[str, int]:
        """Start (or return) the streamed bulk-ingest sink: a
        :class:`FeatureSinkServer` whose pipelined ``feature`` op lands
        each pattern straight in the journal + catalog (the streaming
        client's ``sync`` ack vouches for durability, exactly the
        map-fleet contract), with distribution to the shard holders
        DEFERRED to one ``gflush`` round-trip over persistent links —
        loading 10^5 patterns is a streamed pipeline, not 10^5
        register() round-trips."""
        with self._lock:
            sink = self._bulk
        if sink is not None:
            return sink.address
        fresh = FeatureSinkServer(
            on_feature=self._bulk_feature, on_request=self._bulk_request,
        )
        addr = fresh.start()
        with self._lock:
            if self._bulk is None:
                self._bulk = fresh
                return addr
            sink = self._bulk
        fresh.close()  # lost the creation race
        return sink.address

    def _bulk_feature(self, shard: str, name: str, arr) -> None:
        """One streamed pattern: journal FIRST, then catalog — the
        register() durability ordering, minus the per-call push. A
        raise here is counted on the sink connection and dirties the
        client's next sync ack, which fails the batch attempt."""
        name = str(name)
        arr = np.ascontiguousarray(np.asarray(arr, np.float32))
        kr = int(arr.shape[0]) if arr.ndim >= 1 else 1
        sh = self.shard_of(name)
        payload = pack_array(arr)
        if self._journal is not None:
            self._journal.record(name, sh, payload, kr)
        entry = {
            "name": name,
            "shard": sh,
            "k_real": kr,
            "payload": payload,
            "digest": _payload_digest(arr.tobytes()),
            "copies": set(),
        }
        with self._lock:
            self._patterns[name] = entry
            self._counters["registrations"] += 1
            self._counters["bulk_registered"] += 1

    def _bulk_request(self, doc: dict, state: dict) -> Optional[dict]:
        if doc.get("op") != "gflush":
            return None
        return {"op": "gflush", "ok": True, **self.flush_pending()}

    def flush_pending(self) -> dict:
        """Distribute every catalog pattern with no acknowledged copy
        yet (the bulk path journals + catalogs only) to the shard
        holders + mirrors, over ONE persistent data-plane link per
        worker. Idempotent — re-running touches only what is still
        copy-less."""
        with self._lock:
            pending = [dict(e, copies=e["copies"])
                       for e in self._patterns.values()
                       if not e["copies"]]
        links: Dict[str, _ExtractLink] = {}
        pushed = under = 0
        try:
            for entry in pending:
                copies = self._distribute(entry, links=links)
                pushed += copies
                if copies < min(self.replicas,
                                max(len(self._svc.live_workers()), 1)):
                    under += 1
        finally:
            for link in links.values():
                link.close()
        if under:
            self._count("under_replicated", under)
        self._count("bulk_flushes")
        return {"patterns": len(pending), "copies": pushed,
                "under_replicated": under}

    # --------------------------------------------------------- replication
    def _distribute(self, entry: dict, *,
                    links: Optional[Dict[str, _ExtractLink]] = None
                    ) -> int:
        """Push one pattern to its shard's primary and mirror it to
        R−1 other live workers; returns how many copies acknowledged."""
        shard = entry["shard"]
        copies = 0
        primary = None
        resolved = self.holder_for(shard)
        if resolved is not None:
            primary = resolved[0]
            if self._push_pattern(entry, primary, resolved[2],
                                  replica=False, links=links):
                copies += 1
        copies += self._mirror(entry,
                               exclude={primary} if primary else set(),
                               links=links)
        return copies

    def _mirror(self, entry: dict, exclude: set, *,
                links: Optional[Dict[str, _ExtractLink]] = None) -> int:
        """Top replication back up to R copies on live workers."""
        live = self._svc.live_workers()
        with self._lock:
            have = {w for w in entry["copies"] if w in live}
        need = self.replicas - len(have) - len(exclude - have)
        acked = 0
        for wid in sorted(live):
            if need <= acked:
                break
            if wid in have or wid in exclude:
                continue
            addr = self._addr_of(wid)
            if addr is None:
                continue
            if self._push_pattern(entry, wid, addr, replica=True,
                                  links=links):
                acked += 1
        return acked

    def _push_link(self, links: Optional[Dict[str, _ExtractLink]],
                   wid: str, addr: Tuple[str, int]
                   ) -> Optional[_ExtractLink]:
        """The caller-owned persistent link for one worker during a
        bulk flush (None = use per-push oneshot, the default path). A
        dead link is replaced so a retry reconnects."""
        if links is None:
            return None
        link = links.get(wid)
        if link is not None and not link.dead \
                and link.address == (addr[0], int(addr[1])):
            return link
        try:
            links[wid] = _ExtractLink(addr, self._push_timeout)
        except OSError:
            return None
        return links[wid]

    def _push_pattern(self, entry: dict, wid: str,
                      addr: Tuple[str, int], *, replica: bool,
                      tries: int = 3,
                      links: Optional[Dict[str, _ExtractLink]] = None
                      ) -> bool:
        """One copy onto one worker, digest-verified end to end. The
        ``gallery.replica`` fault point fires (and may corrupt the
        payload bytes) per REPLICA push attempt; a corrupt copy is
        rejected by the worker's digest check and retried clean —
        counted, never silently installed."""
        raw = base64.b64decode(entry["payload"]["b64"])
        for attempt in range(max(tries, 1)):
            data = raw
            try:
                with faults.shard_scope(entry["shard"], attempt):
                    if replica:
                        faults.fire("gallery.replica")
                        data = faults.corrupt_bytes("gallery.replica", raw)
                doc = {
                    "op": "pattern",
                    "name": entry["name"],
                    "shard": entry["shard"],
                    "k_real": entry["k_real"],
                    "replica": bool(replica),
                    "digest": entry["digest"],
                    "payload": {
                        "b64": base64.b64encode(data).decode("ascii"),
                        "dtype": entry["payload"]["dtype"],
                        "shape": list(entry["payload"]["shape"]),
                    },
                }
                if replica:
                    self._count("replica_pushes")
                link = self._push_link(links, wid, addr)
                if link is not None:
                    reply = link.call(doc)
                    if reply is None:
                        raise ConnectionError("bulk push link died")
                else:
                    reply = oneshot(addr, doc, timeout=self._push_timeout)
            except Exception:
                # injected raise or a dead worker: this attempt is
                # gone; the retry (or the journal) owns durability
                self._count("push_failures")
                continue
            if reply.get("ok") is True:
                with self._lock:
                    ent = self._patterns.get(entry["name"])
                    if ent is not None:
                        ent["copies"].add(wid)
                return True
            if reply.get("status") == "corrupt":
                self._count("replica_corrupt")
                continue
            self._count("push_failures")
        return False

    def _materialize(self, part: PatternShard, epoch: int,
                     wid: str) -> None:
        """Re-materialize one shard onto its (possibly new) holder:
        adopt from the worker's replica store first (digest-checked),
        push the rest from the catalog, then heal replication."""
        with self._lock:
            pats = [
                dict(e, copies=e["copies"]) for e in
                self._patterns.values() if e["shard"] == part.index
            ]
        if not pats:
            return
        addr = self._addr_of(wid)
        if addr is None:
            self._count("materialize_errors")
            return
        installed: set = set()
        try:
            adopt = oneshot(addr, {
                "op": "adopt", "shard": part.index, "epoch": int(epoch),
                "patterns": [
                    {"name": p["name"], "digest": p["digest"],
                     "k_real": p["k_real"]} for p in pats
                ],
            }, timeout=self._push_timeout)
            if adopt.get("ok") is True:
                installed = set(adopt.get("installed") or ())
        except Exception:
            self._count("materialize_errors")
        if installed:
            self._count("adopt_installed", len(installed))
            with self._lock:
                for p in pats:
                    if p["name"] in installed:
                        ent = self._patterns.get(p["name"])
                        if ent is not None:
                            ent["copies"].add(wid)
        for p in pats:
            if p["name"] in installed:
                continue
            if self._push_pattern(p, wid, addr, replica=False):
                self._count("adopt_pushed")
            else:
                self._count("materialize_errors")
        if part.assignments > 1:
            self._count("promotions")
        with self._lock:
            fresh = [dict(e, copies=e["copies"]) for e in
                     self._patterns.values() if e["shard"] == part.index]
        for p in fresh:
            self._mirror(p, exclude=set())

    # --------------------------------------------------------------- state
    def client(self, **kw) -> "GalleryFleetClient":
        return GalleryFleetClient(self, **kw)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def state(self) -> dict:
        with self._svc.lock:
            with self._lock:
                return {
                    "ok": True,
                    "shards": {
                        s.key: {
                            "status": s.status,
                            "holder": self._svc.holder(s.index),
                            "assignments": s.assignments,
                        }
                        for s in self._shards
                    },
                    "patterns": len(self._patterns),
                    "workers": {
                        w.wid: {"drained": w.drained, "dead": w.dead,
                                "banks": self._beat_banks.get(w.wid)}
                        for w in self._svc.workers.values()
                    },
                    "reassignments": [
                        dict(r) for r in self._svc.reassignments
                    ],
                    "counters": dict(self._counters),
                }


# ---------------------------------------------------------------- worker
class GalleryFleetWorker:
    """One gallery worker: joins a :class:`GalleryFleet`, leases
    pattern shards, heartbeats them, and answers fenced ``gsearch``
    round-trips on its data plane (a
    :class:`~tmr_tpu.serve.gallery.FeatureSinkServer` composed through
    ``on_request``, exactly like the feature tier's workers).

    ``bank_factory(shard_index)`` builds the per-shard bank — a real
    :class:`~tmr_tpu.serve.gallery.GalleryBank` in production,
    :class:`StubGalleryBank` in the harnesses. Replica payloads live
    in a host-side store until promotion installs them; only the held
    shard's bank serves searches (``gsearch`` is epoch-fenced — a
    revoked worker answers ``fenced``, never stale detections)."""

    def __init__(self, coordinator: Tuple[str, int], worker_id: str, *,
                 bank_factory: Callable[[int], Any],
                 data_host: str = "127.0.0.1", data_port: int = 0,
                 timeout: float = 30.0):
        self.worker_id = worker_id
        self._bank_factory = bank_factory
        self.coordinator = (coordinator[0], int(coordinator[1]))
        self._lock = threading.RLock()
        self._held: Dict[int, int] = {}  # shard index -> epoch
        self._banks: Dict[int, Any] = {}
        self._installed: Dict[int, set] = {}
        #: replica store: pattern name -> the full wire entry (payload
        #: + digest) — promotion re-materializes banks from here
        self._store: Dict[str, dict] = {}
        self._stop_event = threading.Event()
        self._drained = False
        self._coordinator_lost = False
        self._counters = {
            "searches": 0, "fenced": 0, "errors": 0,
            "patterns_stored": 0, "patterns_installed": 0,
            "corrupt_rejected": 0, "evicted": 0,
        }
        self._sink = FeatureSinkServer(
            host=data_host, port=data_port,
            on_request=self._on_request,
        )
        data_addr = self._sink.start()
        self._sock = socket.create_connection(
            self.coordinator, timeout=connect_timeout(min(timeout, 5.0))
        )
        self._sock.settimeout(timeout)
        self._file = self._sock.makefile("rb")
        self._ctl_lock = threading.Lock()
        self.config = self._call({
            "op": "hello",
            "data_addr": list(data_addr[:2]),
        })
        self._hb_interval = float(
            self.config.get("hb_interval_s") or 2.5
        )
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- control
    def _call(self, doc: dict) -> dict:
        doc = dict(doc)
        doc.setdefault("worker", self.worker_id)
        with self._ctl_lock:
            send_line(self._sock, doc)
            reply = recv_line(self._file)
        if reply is None:
            raise ConnectionError("gallery-fleet coordinator closed the "
                                  "connection")
        return reply

    def start(self) -> "GalleryFleetWorker":
        threads = [
            threading.Thread(target=self._lease_loop,
                             name=f"gal-lease-{self.worker_id}",
                             daemon=True),
            threading.Thread(target=self._beat_loop,
                             name=f"gal-beat-{self.worker_id}",
                             daemon=True),
        ]
        with self._lock:
            self._threads = threads
        for t in threads:
            t.start()
        return self

    def _lease_loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                grant = self._call({"op": "lease"})
            except (ConnectionError, OSError):
                if not self._stop_event.is_set():
                    with self._lock:
                        self._coordinator_lost = True
                return
            if grant.get("drained"):
                with self._lock:
                    self._drained = True
                return
            index = grant.get("index")
            if index is None:
                if self._stop_event.wait(
                    float(grant.get("wait_s", 0.2))
                ):
                    return
                continue
            with self._lock:
                self._held[int(index)] = int(grant["epoch"])

    def _beat_loop(self) -> None:
        while not self._stop_event.wait(self._hb_interval):
            try:
                # the gallery.beat point is the SIGSTOP stand-in:
                # latency=S delays the beat past the TTL (the shard
                # goes stale and is promoted onto a replica); a raise
                # just drops this beat — both ARE the liveness signal
                faults.fire("gallery.beat")
                self._beat_once()
            except (ConnectionError, OSError):
                pass
            except Exception:
                if not faults.active():
                    raise

    def _beat_once(self) -> dict:
        with self._lock:
            held = [[i, e] for i, e in self._held.items()]
            banks = {
                str(i): self._bank_beat_stats(self._banks[i])
                for i, _ in held if i in self._banks
            }
        reply = oneshot(self.coordinator, {
            "op": "beat", "worker": self.worker_id, "held": held,
            "banks": banks,
        })
        stale = reply.get("stale") or ()
        with self._lock:
            for index, epoch in stale:
                if self._held.get(int(index)) == int(epoch):
                    del self._held[int(index)]
            if reply.get("drained"):
                self._drained = True
        return reply

    @staticmethod
    def _bank_beat_stats(bank) -> dict:
        """The per-shard payload a heartbeat carries: entry count plus
        the bank's sketch-index stats when it has them (a real
        GalleryBank's ``index_stats`` is beat-light by design; stubs
        just report size)."""
        rec = {"entries": len(bank)}
        stats_fn = getattr(bank, "index_stats", None)
        if callable(stats_fn):
            try:
                rec["index"] = stats_fn()
            except Exception:
                pass  # a beat must never die on a stats probe
        return rec

    # ---------------------------------------------------------- data plane
    def holds(self, index: int, epoch: int) -> bool:
        with self._lock:
            return self._held.get(int(index)) == int(epoch)

    def _bank_for(self, shard: int):
        with self._lock:
            bank = self._banks.get(shard)
            if bank is None:
                bank = self._banks[shard] = self._bank_factory(shard)
                self._installed.setdefault(shard, set())
            return bank

    def _install(self, entry: dict) -> None:
        shard = int(entry["shard"])
        bank = self._bank_for(shard)
        arr = unpack_array(entry["payload"])
        bank.register(entry["name"], arr, k_real=int(entry["k_real"]))
        with self._lock:
            self._installed.setdefault(shard, set()).add(entry["name"])
            self._counters["patterns_installed"] += 1

    def _on_request(self, doc: dict, state: dict) -> Optional[dict]:
        op = doc.get("op")
        if op == "pattern":
            return self._op_pattern(doc)
        if op == "adopt":
            return self._op_adopt(doc)
        if op == "evict_pattern":
            return self._op_evict(doc)
        if op == "gsearch":
            return self._op_gsearch(doc)
        if op == "gstate":
            return self._op_gstate(doc)
        return None  # unknown ops fall through to the sink's error

    def _op_pattern(self, doc: dict) -> dict:
        raw = base64.b64decode(doc["payload"]["b64"])
        if _payload_digest(raw) != doc.get("digest"):
            # a corrupt copy must NEVER enter the store: the digest
            # check is the replica-integrity contract the chaos probe
            # injects against
            with self._lock:
                self._counters["corrupt_rejected"] += 1
            return {"op": "pattern", "ok": False, "status": "corrupt",
                    "name": doc.get("name")}
        entry = {
            "name": str(doc["name"]),
            "shard": int(doc["shard"]),
            "k_real": int(doc["k_real"]),
            "payload": dict(doc["payload"]),
            "digest": str(doc["digest"]),
        }
        with self._lock:
            self._store[entry["name"]] = entry
            self._counters["patterns_stored"] += 1
        if not doc.get("replica"):
            self._install(entry)
        return {"op": "pattern", "ok": True, "status": "ok",
                "name": entry["name"], "replica": bool(doc.get("replica"))}

    def _op_adopt(self, doc: dict) -> dict:
        shard = int(doc.get("shard", -1))
        installed: List[str] = []
        missing: List[str] = []
        for want in doc.get("patterns") or ():
            name = str(want.get("name"))
            with self._lock:
                ent = self._store.get(name)
                already = name in self._installed.get(shard, set())
            if already:
                installed.append(name)
                continue
            if ent is None or ent["digest"] != want.get("digest") \
                    or ent["shard"] != shard:
                missing.append(name)
                continue
            self._install(ent)
            installed.append(name)
        return {"op": "adopt", "ok": True, "shard": shard,
                "installed": installed, "missing": missing}

    def _op_evict(self, doc: dict) -> dict:
        name = str(doc.get("name"))
        shard = int(doc.get("shard", -1))
        with self._lock:
            self._store.pop(name, None)
            bank = self._banks.get(shard)
            had = name in self._installed.get(shard, set())
            self._installed.get(shard, set()).discard(name)
            self._counters["evicted"] += 1
        if bank is not None and had:
            bank.evict(name)
        return {"op": "evict_pattern", "ok": True, "name": name}

    def _op_gsearch(self, doc: dict) -> dict:
        shard = int(doc.get("shard", -1))
        epoch = int(doc.get("epoch", -1))
        with _fleetobs.op_span(doc, "gallery.worker.gsearch",
                               shard=shard) as span:
            if not self.holds(shard, epoch):
                with self._lock:
                    self._counters["fenced"] += 1
                span.set_attr(status="fenced")
                return {"op": "gsearch", "ok": False,
                        "status": "fenced"}
            try:
                image = unpack_array(doc["image"])
                with self._lock:
                    bank = self._banks.get(shard)
                results = bank.search(image) if bank is not None else {}
            except Exception as e:
                with self._lock:
                    self._counters["errors"] += 1
                span.set_attr(status="error")
                return {"op": "gsearch", "ok": False, "status": "error",
                        "message": f"{type(e).__name__}: {e}"}
            with self._lock:
                self._counters["searches"] += 1
            span.set_attr(status="ok")
            return {"op": "gsearch", "ok": True, "status": "ok",
                    "shard": shard, "results": pack_results(results)}

    def _op_gstate(self, doc: dict) -> dict:
        with self._lock:
            return {
                "op": "gstate", "ok": True, "worker": self.worker_id,
                "held": {str(i): e for i, e in self._held.items()},
                "stored": sorted(self._store),
                "installed": {
                    str(s): sorted(names)
                    for s, names in self._installed.items()
                },
                "banks": {
                    str(s): self._bank_beat_stats(b)
                    for s, b in self._banks.items()
                },
                "counters": dict(self._counters),
                "faults_active": faults.active(),
                "faults_fired": len(faults.fired()),
            }

    # ------------------------------------------------------------ lifecycle
    @property
    def held(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._held)

    @property
    def drained(self) -> bool:
        with self._lock:
            return self._drained

    @property
    def coordinator_lost(self) -> bool:
        with self._lock:
            return self._coordinator_lost

    @property
    def data_address(self) -> Tuple[str, int]:
        return self._sink.address

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_event.set()
        try:
            self._call({"op": "bye"})
        except (ConnectionError, OSError):
            pass
        try:  # shutdown-first: unblocks any reader before the close
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._sink.close()
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))


# ---------------------------------------------------------------- client
class GalleryFleetClient:
    """The fan-out front door: one frame goes to every worker holding
    one of its shards; the disjoint per-shard results union back into
    the single bank's ``{name: dets}`` shape.

    Degrade contract: a shard with no live holder, a severed link
    (the ``serve.link`` fault point fires per fan-out write), or a
    fenced/raced reply yields that shard's patterns as empty
    detections labeled ``degrade_steps: ["partition_unavailable"]`` —
    counted, never an error — and heals on the next search once the
    lease rebalances. With every shard healthy the merged result is
    byte-identical to one bank holding all patterns (per-entry results
    are independent of bank co-residents — PR 15's per-entry bitwise
    pin — and the wire codec is exact bytes)."""

    def __init__(self, fleet: GalleryFleet, *,
                 timeout_s: Optional[float] = None):
        self._fleet = fleet
        self._timeout_s = (
            _env_float("TMR_GALLERY_FLEET_TIMEOUT_S", 10.0)
            if timeout_s is None else float(timeout_s)
        )
        self._lock = threading.Lock()
        self._links: Dict[str, _ExtractLink] = {}
        #: per-shard fan-out attempt numbers — the ambient attempt the
        #: serve.link fault point scopes by (attempts=1 severs the
        #: first fan-out to a shard and lets the retry heal)
        self._attempts: Dict[int, int] = {}
        self._counters = {
            "searches": 0, "fanouts": 0, "merged_patterns": 0,
            "degraded_shards": 0, "degraded_patterns": 0,
            "no_holder": 0, "link_failures": 0, "fenced": 0,
            "errors": 0,
        }

    def _bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def _link_for(self, wid: str,
                  addr: Tuple[str, int]) -> Optional[_ExtractLink]:
        with self._lock:
            link = self._links.get(wid)
        if link is not None and not link.dead \
                and link.address == (addr[0], int(addr[1])):
            return link
        try:
            fresh = _ExtractLink(addr, self._timeout_s)
        except OSError:
            return None
        with self._lock:
            old = self._links.get(wid)
            self._links[wid] = fresh
        if old is not None:
            old.close()
        return fresh

    def _drop_link(self, wid: str) -> None:
        with self._lock:
            link = self._links.pop(wid, None)
        if link is not None:
            link.close()

    def _fetch_shard(self, shard: int, image_doc: dict,
                     ctx: Optional[dict] = None
                     ) -> Optional[Dict[str, dict]]:
        with self._lock:
            attempt = self._attempts.get(shard, 0)
            self._attempts[shard] = attempt + 1
        resolved = self._fleet.holder_for(shard)
        if resolved is None:
            self._bump("no_holder")
            return None
        wid, epoch, addr = resolved
        link = self._link_for(wid, addr)
        if link is None:
            self._bump("link_failures")
            return None
        try:
            with faults.shard_scope(shard, attempt):
                # an injected raise here IS a severed data link: drop
                # the connection and degrade this shard for this frame
                faults.fire("serve.link")
        except Exception:
            self._drop_link(wid)
            self._bump("link_failures")
            return None
        doc = {
            "op": "gsearch", "shard": int(shard), "epoch": int(epoch),
            "image": image_doc,
        }
        if ctx is not None:
            doc["ctx"] = ctx  # the search root's trace follows the hop
        reply = link.call(doc)
        if reply is None:
            self._bump("link_failures")
            return None
        if reply.get("ok") is not True:
            self._bump("fenced" if reply.get("status") == "fenced"
                       else "errors")
            return None
        return unpack_results(reply.get("results") or {})

    def search(self, image) -> Dict[str, dict]:
        """Fan out one frame to every pattern shard's holder and merge
        — the single bank's ``search`` surface, cluster-sized."""
        img = np.ascontiguousarray(np.asarray(image, np.float32))
        image_doc = pack_array(img)
        plan = self._fleet.shard_map()
        self._bump("searches")
        # the gallery search front door mints ONE trace id for the
        # whole fan-out; every shard hop parents under it
        root = _fleetobs.root_span("gallery.search", shards=len(plan))
        ctx = root.ctx() if root is not None else None
        results: Dict[str, dict] = {}
        for shard in sorted(plan):
            names = plan[shard]
            if not names:
                continue
            self._bump("fanouts")
            got = self._fetch_shard(shard, image_doc, ctx)
            if got is None:
                self._bump("degraded_shards")
                self._bump("degraded_patterns", len(names))
                for name in names:
                    results[name] = unavailable_result()
                continue
            for name in names:
                dets = got.get(name)
                if dets is None:
                    # the holder has the lease but not (yet) this
                    # pattern — degrade exactly that entry
                    self._bump("degraded_patterns")
                    results[name] = unavailable_result()
                else:
                    self._bump("merged_patterns")
                    results[name] = dets
        if root is not None:
            root.close()
        return results

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()


# ------------------------------------------------------------ bulk client
def bulk_register(sink_addr: Tuple[str, int], patterns, *,
                  batch: str = "bulk", timeout_s: Optional[float] = None,
                  flush: bool = True,
                  flush_timeout_s: float = 600.0) -> dict:
    """Stream ``(name, exemplars)`` pairs into a :class:`GalleryFleet`
    bulk-ingest sink (``fleet.bulk_sink()``'s address) over ONE
    pipelined connection — the map fleet's feature-sink protocol
    reused as the gallery's bulk-register path.

    Every pattern rides a no-reply ``feature`` op (``k_real`` = the
    exemplar row count); the trailing ``sync`` ack vouches that all of
    them are journaled + cataloged (``ok`` goes False on any count
    mismatch or sink-side error — re-stream the batch). With ``flush``
    (default) one ``gflush`` round-trip then distributes everything
    copy-less to the shard holders over persistent links; pass
    ``flush=False`` when streaming several batches before one
    ``fleet.flush_pending()``.
    """
    timeout = (
        _env_float("TMR_GALLERY_FLEET_TIMEOUT_S", 30.0)
        if timeout_s is None else float(timeout_s)
    )
    sock = socket.create_connection(
        (sink_addr[0], int(sink_addr[1])),
        timeout=connect_timeout(min(timeout, 5.0)),
    )
    sock.settimeout(timeout)
    f = sock.makefile("rb")
    streamed = 0
    try:
        send_line(sock, {"op": "hello", "worker": f"bulk-{batch}"})
        if (recv_line(f) or {}).get("ok") is not True:
            raise ConnectionError("bulk sink refused hello")
        for name, exemplars in patterns:
            arr = np.ascontiguousarray(np.asarray(exemplars, np.float32))
            send_line(sock, {
                "op": "feature", "shard": str(batch),
                "name": str(name), "array": pack_array(arr),
            })
            streamed += 1
        send_line(sock, {"op": "sync", "shard": str(batch)})
        sync = recv_line(f) or {}
        ok = (sync.get("ok") is True
              and int(sync.get("features", -1)) == streamed)
        out = {
            "streamed": streamed,
            "synced": int(sync.get("features", 0)),
            "errors": int(sync.get("errors", 0)),
            "ok": ok,
        }
        if flush and ok:
            # distribution fans out to every holder before replying —
            # a catalog-sized flush outlives the per-line timeout
            sock.settimeout(max(float(flush_timeout_s), timeout))
            send_line(sock, {"op": "gflush"})
            reply = recv_line(f) or {}
            out["flush"] = {
                key: reply.get(key)
                for key in ("ok", "patterns", "copies",
                            "under_replicated")
            }
            out["ok"] = ok and reply.get("ok") is True
        try:
            send_line(sock, {"op": "bye"})
            recv_line(f)
        except (OSError, ValueError):
            pass
        return out
    finally:
        try:
            sock.close()
        except OSError:
            pass


# ------------------------------------------------------------------ stub
class StubGalleryBank:
    """A dependency-free bank with the :class:`GalleryBank` surface
    (register/evict/search) whose detections depend ONLY on
    (pattern exemplars, frame) — float32 arithmetic, deterministic
    across processes — so fan-out-vs-single-bank equality through this
    stub is a genuine end-to-end wire check: crossed shards, stale
    payloads, or a lossy codec all show as byte mismatches."""

    def __init__(self, image_size: int = 32):
        self.image_size = int(image_size)
        self._lock = threading.Lock()
        self._entries: Dict[str, Tuple[np.ndarray, int]] = {}

    def register(self, name: str, exemplars, k_real: Optional[int] = None
                 ) -> dict:
        arr = np.ascontiguousarray(np.asarray(exemplars, np.float32))
        kr = int(k_real) if k_real is not None else int(
            arr.shape[0] if arr.ndim >= 1 else 1
        )
        with self._lock:
            self._entries[str(name)] = (arr, kr)
        return {"name": str(name), "k_real": kr,
                "capacity": int(arr.size), "k_bucket": kr}

    def evict(self, name: str) -> bool:
        with self._lock:
            return self._entries.pop(str(name), None) is not None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def search(self, image, prefilter_topk: Optional[int] = None
               ) -> Dict[str, dict]:
        img = np.asarray(image, np.float32)
        sig = np.float32(img.mean(dtype=np.float32))
        with self._lock:
            entries = list(self._entries.items())
        out: Dict[str, dict] = {}
        for name, (ex, kr) in entries:
            exsum = np.float32(ex.sum(dtype=np.float32))
            score = np.float32(sig + exsum)
            out[name] = {
                "boxes": np.asarray(
                    [[[0.0, 0.0, float(exsum), float(sig)]]], np.float32
                ),
                "scores": np.asarray([[score]], np.float32),
                "refs": np.zeros((1, 1, 2), np.float32),
                "valid": np.ones((1, 1), bool),
                "count": np.asarray([kr], np.int32),
            }
        return out
