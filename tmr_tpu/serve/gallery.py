"""Gallery tier: persistent template banks, one-backbone-pass
multi-pattern matching, and a coarse prefilter for streaming-image
search.

The paper's contract is 1-3 exemplars per request; production serves
STANDING pattern sets — watchlists, catalog SKUs, defect libraries —
against image streams, where a naive deployment pays one full request
(backbone included) per (frame, pattern) pair. This tier closes that
multiplier over the existing backbone/heads split programs:

- **Bank registry** (:class:`GalleryBank`): register/evict named
  exemplar sets. Registration does ALL the per-pattern work once — the
  odd template-capacity bucket is picked from the exemplar geometry
  (``ops/xcorr.template_geometry``'s host mirror,
  ``select_capacity_bucket``), the boxes pad onto the static
  (k-bucket) grid, entries bucket by (capacity, k_bucket), and each
  bucket's pattern tensors are placed device-resident — so per-frame
  work never re-processes or re-uploads a pattern. (The (C, T, T)
  template values themselves are functions of the FRAME's features in
  this model — extraction is two tiny einsums fused into the device
  program on the pre-staged grid; hoisting them would break the bitwise
  contract below.)
- **Fused gallery-vs-image matching**: one backbone pass per frame.
  Cold frames run ``Predictor._get_gallery_fn`` — backbone + N·k
  matcher/heads rows + per-entry union NMS in ONE program, per-entry
  results bitwise-identical to an N-loop of ``predict_multi_exemplar``
  (pinned by tests/test_gallery.py; gate below). Hot frames ride the
  feature cache with second-sighting promotion exactly like the serve
  engine — backbone program once, then ``_get_gallery_heads_fn`` per
  bucket (the documented heads-path last-ULP exception). Bank sizes pad
  to the ``N_BUCKETS`` rung ladder with ``n_real`` masking, so ragged
  bank sizes inside a rung never recompile; the ladder cap is
  autotune-elected like the batch bound
  (``utils/autotune.measured_gallery_nmax``).
- **Coarse prefilter** (``TMR_GALLERY_PREFILTER_TOPK``; off = exact):
  a channel-sketched, low-resolution NCC-style score per bank entry
  (``ops/xcorr.coarse_prefilter_scores`` — fixed ±1 Rademacher sketch,
  spatially pooled, per-frame zero-meaned) ranks which entries earn the
  full match+decode. Entries outside the top-k return empty results
  carrying ``degrade_steps: ["prefilter"]`` — the degrade ladder's
  exactness contract: approximation is never silent.
  ``scripts/gallery_bench.py`` measures recall-vs-full-match at the
  elected top-k and emits the validated ``gallery_report/v1``.
- **Feature sink** (:class:`FeatureSinkServer`): elastic map workers
  stream extracted features straight into a serve-side feature index
  over the fleet data-link JSON-lines protocol
  (``parallel/elastic.make_feature_sinks`` with a ``tcp://`` target)
  instead of bouncing through ``.npy`` trees — the deferred half of
  PR 10's elastic item.

- **Sketch index** (``TMR_GALLERY_INDEX``; off = today's linear scan,
  bitwise): at catalog scale the prefilter itself goes sublinear — a
  two-level IVF-style index (serve/gallery_index.py) clusters entries
  by box geometry into ~sqrt(N) buckets, one batched device call
  scores each bucket's probes (medoid + anti-medoid), and the exact
  sketch correlation runs
  only over the ``TMR_GALLERY_INDEX_NPROBE`` best buckets' members
  before feeding the SAME top-k selection (degrade labels, counters,
  and the exactness contract carry over unchanged). Any index-path
  failure falls back to the exact linear scan, counted.

Env knobs (lazily read; registered in config.ENV_KNOBS):
``TMR_GALLERY_PREFILTER_TOPK`` (0/unset = off = exact; ``auto`` = the
bench-elected winner; int = that top-k), ``TMR_GALLERY_NMAX`` (N-bucket
ladder cap; default the measured winner, else 32),
``TMR_GALLERY_FEATURE_CACHE`` (frame-feature cache entries),
``TMR_GALLERY_FEATURE_CACHE_MB`` (byte bound on the same cache),
``TMR_GALLERY_INDEX`` (sketch index on/off; off default = exact
linear prefilter), ``TMR_GALLERY_INDEX_NPROBE`` (buckets probed per
query; 0 = auto = max(2*ceil(sqrt(C)), min(C, topk))),
``TMR_GALLERY_INDEX_MIN_N`` (banks
below this stay linear even with the index on),
``TMR_GALLERY_INDEX_REBUILD`` (churn fraction triggering a rebuild).
"""

from __future__ import annotations

import os
import socketserver
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tmr_tpu.obs.metrics import MetricsRegistry
from tmr_tpu.serve.caches import LRUCache, array_digest
from tmr_tpu.serve.gallery_index import SketchIndex, entry_sketch

#: detection fields a gallery result carries (mirrors engine._det_fields:
#: the fixed four plus the device decode tail's optional count vector)
_DET_FIELDS = ("boxes", "scores", "refs", "valid", "count")

#: the bank's counter names, registered as ``gallery.<name>`` —
#: full_match_entries is the prefilter-cut denominator the bench reads
_COUNTER_NAMES = (
    "searches", "fused_frames", "heads_frames", "backbone_fills",
    "registered", "evicted", "full_match_entries", "prefilter_runs",
    "prefilter_skipped", "nloop_fallback_frames", "index_queries",
    "index_probes", "index_hits", "index_candidates", "index_rebuilds",
    "index_fallbacks",
)

#: above this many scored entries the per-name score dict keeps only
#: the SELECTED entries (skipped large-N tails would otherwise pay an
#: O(N) host dict per frame just to decorate empty results)
_SCORE_TAIL_MAX = 4096

#: flat batched prefilter calls chunk at this many entries per device
#: call — coarse_prefilter_scores broadcasts the frame's feature map
#: per (entry, row), so unbounded batches explode memory at 10^5 N
_INDEX_CHUNK = 1024


def _topk_flat(scores: np.ndarray, k: int) -> np.ndarray:
    """Top-k indices of ``scores`` with ties resolved EXACTLY like the
    historic stable ``ranked.sort(key=-score)`` selection: every entry
    strictly above the k-th value, then kth-valued ties in ascending
    flat order. O(N) argpartition instead of the old O(N log N) sort."""
    n = int(scores.shape[0])
    if k >= n:
        return np.arange(n)
    kth = np.partition(scores, n - k)[n - k]
    above = np.flatnonzero(scores > kth)
    ties = np.flatnonzero(scores == kth)
    return np.concatenate([above, ties[: k - above.size]])


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------- the gate
_GATE_CACHE: dict = {}
_GATE_LOCK = threading.Lock()


def gallery_fused_ok(predictor, capacity: int, n_bucket: int,
                     k_bucket: int) -> bool:
    """Trace-only gate for the fused gallery program (the program-audit
    pattern: ``make_jaxpr`` over abstract inputs, no compile): the
    program's jaxpr at this geometry must consume the frame through
    exactly ONE backbone entry convolution — the backbone-amortization
    invariant the whole tier exists for. A duplicated backbone (N of
    them) would silently restore the frames×N cost while reading as
    "fused".

    A refusal records a ``gate_probe/v1`` cause (scripts/gate_probe.py
    probes this gate) and the gallery tier routes cold frames through
    the split backbone+heads programs instead — still one backbone pass
    per frame by construction; what is given up is the fused arm's
    bitwise contract. Verdicts cache per geometry.
    """
    from tmr_tpu.diagnostics import gate_refused

    key = (int(capacity), int(n_bucket), int(k_bucket),
           int(predictor.cfg.image_size), str(predictor.cfg.backbone))
    with _GATE_LOCK:
        if key in _GATE_CACHE:
            return _GATE_CACHE[key]
    config = {"capacity": key[0], "n_bucket": key[1], "k_bucket": key[2],
              "image_size": key[3], "backbone": key[4]}
    ok = False
    try:
        import jax
        import jax.numpy as jnp

        from tmr_tpu.analysis.program_audit import iter_eqns
        from tmr_tpu.inference import _PassthroughBackbone

        size = int(predictor.cfg.image_size)
        model = predictor.model.clone(template_capacity=int(capacity))
        heads = model.clone(backbone=_PassthroughBackbone())
        tail = predictor._gallery_tail(heads, int(n_bucket),
                                       int(k_bucket), False)
        image = jax.ShapeDtypeStruct((1, size, size, 3), jnp.float32)
        ex = jax.ShapeDtypeStruct((int(n_bucket), int(k_bucket), 4),
                                  jnp.float32)
        kr = jax.ShapeDtypeStruct((int(n_bucket),), jnp.int32)
        nr = jax.ShapeDtypeStruct((), jnp.int32)
        params = predictor.params
        if params is None:
            params = jax.eval_shape(
                model.init, jax.random.key(0), image,
                jax.ShapeDtypeStruct((1, 1, 4), jnp.float32),
            )["params"]

        def body(params, image, exemplars, k_real, n_real):
            feat = model.backbone.apply(
                {"params": params["backbone"]}, image
            )
            if isinstance(feat, (list, tuple)):
                feat = feat[0]
            return tail(params, None, feat, exemplars, k_real, n_real,
                        (size, size))

        jaxpr = jax.make_jaxpr(body)(params, image, ex, kr, nr)
        entry_convs = 0
        for eqn in iter_eqns(getattr(jaxpr, "jaxpr", jaxpr)):
            if eqn.primitive.name != "conv_general_dilated":
                continue
            aval = getattr(eqn.invars[0], "aval", None)
            shape = getattr(aval, "shape", None)
            # the backbone entry conv is the only conv consuming a
            # 3-channel image-layout tensor anywhere in the program
            if shape is not None and len(shape) == 4 and 3 in (
                shape[-1], shape[1]
            ):
                entry_convs += 1
        if entry_convs == 1:
            ok = True
        else:
            ok = gate_refused(
                "gallery_fused_ok",
                f"backbone entry conv traced {entry_convs}x "
                "(amortization requires exactly once per frame)",
                "forward-mismatch", config=config,
            )
    except Exception as e:
        ok = gate_refused(
            "gallery_fused_ok", f"{type(e).__name__}: {e}", "exception",
            config=config, exception=type(e).__name__,
        )
    with _GATE_LOCK:
        # a racing double-trace stores the same verdict twice — benign
        _GATE_CACHE[key] = ok
    return ok


# ------------------------------------------------------------- the registry
class GalleryEntry:
    """One registered pattern: name + its boxes padded onto the static
    (k_bucket) grid, with the capacity bucket picked at registration."""

    __slots__ = ("name", "exemplars", "k_real", "k_bucket", "capacity")

    def __init__(self, name: str, exemplars: np.ndarray, k_real: int,
                 k_bucket: int, capacity: int):
        self.name = name
        self.exemplars = exemplars  # (k_bucket, 4) f32, rows >= k_real pad
        self.k_real = int(k_real)
        self.k_bucket = int(k_bucket)
        self.capacity = int(capacity)


class _Group:
    """One (capacity, k_bucket) bucket chunk of the bank, padded to its
    N rung with the pattern tensors device-resident."""

    __slots__ = ("capacity", "k_bucket", "names", "n_real", "n_bucket",
                 "host_ex", "host_k", "ex_dev", "k_dev", "n_dev")

    def __init__(self, capacity: int, k_bucket: int,
                 members: List[GalleryEntry], n_bucket: int):
        import jax.numpy as jnp

        self.capacity = capacity
        self.k_bucket = k_bucket
        self.names = [e.name for e in members]
        self.n_real = len(members)
        self.n_bucket = n_bucket
        ex = np.stack([e.exemplars for e in members], axis=0)
        kr = np.asarray([e.k_real for e in members], np.int32)
        pad = n_bucket - len(members)
        if pad:
            ex = np.concatenate([ex, np.tile(ex[-1:], (pad, 1, 1))],
                                axis=0)
            kr = np.concatenate([kr, np.ones((pad,), np.int32)])
        self.host_ex = ex
        self.host_k = kr
        # device-resident ONCE at (re)build: per-frame submission moves
        # only the frame — never the patterns
        self.ex_dev = jnp.asarray(ex)
        self.k_dev = jnp.asarray(kr)
        self.n_dev = jnp.asarray(self.n_real, jnp.int32)


class GalleryBank:
    """A standing pattern set over one Predictor, searched per frame
    with one backbone pass (module docstring has the architecture).

    Parameters
    ----------
    predictor: initialized Predictor (params loaded).
    image_size: the stream's frame size (None -> cfg.image_size); a
        bank is pinned to one size (its capacity buckets derive from
        that feature grid), and ``search`` refuses other frames loudly.
    prefilter_topk: coarse-prefilter top-k (None -> the
        ``TMR_GALLERY_PREFILTER_TOPK`` knob; 0 = off = exact).
    feature_cache: frame-feature cache — an int capacity (None ->
        ``TMR_GALLERY_FEATURE_CACHE``, default 8; 0 disables) or an
        existing :class:`LRUCache` to SHARE (e.g. a ServeEngine's, so
        stream frames and interactive traffic amortize one encoder
        pass; keys are the engine's stamped
        (digest, size, params digest, backbone) tuples).
    feature_cache_mb: byte bound on an owned feature cache (None ->
        ``TMR_GALLERY_FEATURE_CACHE_MB``; ignored for a shared cache).
    max_n_bucket: N-rung ladder cap (None -> ``TMR_GALLERY_NMAX`` ->
        the autotune-measured winner -> 32); banks larger than the cap
        chunk into multiple program calls.
    index: force the coarse-to-fine sketch index on/off (None -> the
        ``TMR_GALLERY_INDEX`` knob; off = exact linear prefilter).
    index_nprobe: buckets probed per indexed query (None ->
        ``TMR_GALLERY_INDEX_NPROBE``; 0 = auto = ceil(sqrt(C))).
    index_min_n: banks below this entry count stay on the linear scan
        even with the index on (None -> ``TMR_GALLERY_INDEX_MIN_N``).
    index_rebuild_frac: churn fraction past which queries trigger a
        recluster (None -> ``TMR_GALLERY_INDEX_REBUILD``).
    """

    def __init__(self, predictor, *, image_size: Optional[int] = None,
                 prefilter_topk: Optional[int] = None,
                 feature_cache: Any = None,
                 feature_cache_mb: Optional[float] = None,
                 max_n_bucket: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 index: Optional[bool] = None,
                 index_nprobe: Optional[int] = None,
                 index_min_n: Optional[int] = None,
                 index_rebuild_frac: Optional[float] = None):
        if predictor.params is None:
            raise RuntimeError("predictor has no params loaded")
        self._pred = predictor
        self.image_size = int(image_size or predictor.cfg.image_size)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, GalleryEntry]" = OrderedDict()
        self._groups: Optional[List[_Group]] = None
        self._topk_arg = prefilter_topk
        self._index: Optional[SketchIndex] = None
        self._index_arg = index
        self._index_nprobe_arg = index_nprobe
        self._index_min_n_arg = index_min_n
        self._index_rebuild_arg = index_rebuild_frac
        self.metrics = MetricsRegistry() if registry is None else registry
        self._m = {
            name: self.metrics.counter(f"gallery.{name}")
            for name in _COUNTER_NAMES
        }
        self._g_rebuild_wall = self.metrics.gauge(
            "gallery.index_rebuild_wall_s"
        )
        if isinstance(feature_cache, LRUCache):
            self.feature_cache = feature_cache
        else:
            mb = (_env_float("TMR_GALLERY_FEATURE_CACHE_MB", 0.0)
                  if feature_cache_mb is None else float(feature_cache_mb))
            self.feature_cache = LRUCache(
                _env_int("TMR_GALLERY_FEATURE_CACHE", 8)
                if feature_cache is None else int(feature_cache),
                registry=self.metrics, name="gallery.cache.feature",
                max_bytes=int(mb * (1 << 20)) if mb > 0 else None,
            )
        self._seen = LRUCache(
            max(4 * max(self.feature_cache.capacity, 1), 16)
        )
        #: feature-key provenance (params digest, backbone formulation):
        #: a checkpoint/knob swap can never serve stale frame features —
        #: and a cache SHARED with a ServeEngine over the same predictor
        #: still interoperates (both sides derive the same stamp)
        fstamp = getattr(predictor, "feature_stamp", None)
        self._feat_stamp = tuple(fstamp()) if callable(fstamp) else ()
        if max_n_bucket is not None:
            nmax = int(max_n_bucket)
        else:
            nmax = _env_int("TMR_GALLERY_NMAX", 0)
            if nmax <= 0:
                from tmr_tpu.utils.autotune import measured_gallery_nmax

                nmax = measured_gallery_nmax(self.image_size) or 0
        ladder = tuple(self._pred.N_BUCKETS)
        self.max_n_bucket = (
            max(b for b in ladder if b <= nmax) if nmax > 0 else ladder[-1]
        )

    # ------------------------------------------------------------ registry
    def register(self, name: str, exemplars, k_real: Optional[int] = None
                 ) -> dict:
        """Register (or replace) one named pattern set. All host-side
        pattern work happens HERE, once: k-bucket padding, capacity
        bucketing, and (at the next search) device placement of the
        bucket tensors. Returns the entry's resolved buckets."""
        ex = np.asarray(exemplars, np.float32).reshape(-1, 4)
        k = int(k_real) if k_real is not None else len(ex)
        if not 1 <= k <= len(ex):
            raise ValueError(
                f"k_real={k} out of range for {len(ex)} exemplar rows"
            )
        ex = ex[:k]
        k_bucket = int(next(
            (b for b in self._pred.K_BUCKETS if b >= k), k
        ))
        cap = self._pred.pick_capacity(ex, self.image_size)
        padded = np.concatenate(
            [ex, np.tile(ex[-1:], (k_bucket - k, 1))], axis=0
        )
        with self._lock:
            self._entries[str(name)] = GalleryEntry(
                str(name), padded, k, k_bucket, cap
            )
            if self._index is not None:
                # incremental maintenance: the entry is probe-reachable
                # immediately; churn accounting decides the recluster
                self._index.add(str(name), entry_sketch(padded, k))
            self._groups = None  # rebuilt (and re-placed) lazily
        self._m["registered"].inc()
        return {"name": str(name), "capacity": cap, "k_bucket": k_bucket,
                "k_real": k}

    def evict(self, name: str) -> bool:
        """Drop one named pattern; True when it existed. The bucket
        tensors rebuild on the next search — the device copies of a
        dead entry are not kept resident."""
        with self._lock:
            existed = self._entries.pop(str(name), None) is not None
            if existed:
                if self._index is not None:
                    self._index.remove(str(name))
                self._groups = None
        if existed:
            self._m["evicted"].inc()
        return existed

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return str(name) in self._entries

    def _groups_locked(self) -> List[_Group]:
        """The (capacity, k_bucket)-bucketed device-resident view of the
        registry, rebuilt only when the registry changed. Buckets larger
        than the ladder cap chunk into multiple groups."""
        with self._lock:
            if self._groups is not None:
                return self._groups
            buckets: "OrderedDict[tuple, List[GalleryEntry]]" = \
                OrderedDict()
            for e in self._entries.values():
                buckets.setdefault((e.capacity, e.k_bucket), []).append(e)
            ladder = tuple(self._pred.N_BUCKETS)
            groups: List[_Group] = []
            for (cap, kb), members in buckets.items():
                for i in range(0, len(members), self.max_n_bucket):
                    chunk = members[i:i + self.max_n_bucket]
                    rung = int(next(
                        (b for b in ladder if b >= len(chunk)),
                        len(chunk),
                    ))
                    groups.append(_Group(cap, kb, chunk, rung))
            self._groups = groups
            return groups

    def _feature_key(self, digest: str, size: int) -> tuple:
        """The frame-feature cache key: image digest + size + the
        predictor's (params digest, backbone formulation) stamp, so
        reuse can never cross a checkpoint or formulation swap."""
        return (digest, size) + self._feat_stamp

    # -------------------------------------------------------------- search
    def _resolve_topk(self, override: Optional[int]) -> int:
        if override is not None:
            return max(int(override), 0)
        if self._topk_arg is not None:
            return max(int(self._topk_arg), 0)
        raw = os.environ.get("TMR_GALLERY_PREFILTER_TOPK", "")
        if not raw or raw in ("0", "off", "false"):
            return 0
        if raw == "auto":
            from tmr_tpu.utils.autotune import measured_gallery_topk

            return measured_gallery_topk(self.image_size) or 0
        try:
            return max(int(raw), 0)
        except ValueError:
            raise ValueError(
                f"TMR_GALLERY_PREFILTER_TOPK={raw!r}: expected "
                "off|auto|<int>"
            )

    # ------------------------------------------------------- sketch index
    def _index_enabled(self) -> bool:
        if self._index_arg is not None:
            return bool(self._index_arg)
        raw = os.environ.get("TMR_GALLERY_INDEX", "")
        return bool(raw) and raw not in ("0", "off", "false")

    def _index_min_n_resolved(self) -> int:
        if self._index_min_n_arg is not None:
            return max(int(self._index_min_n_arg), 0)
        return max(_env_int("TMR_GALLERY_INDEX_MIN_N", 256), 0)

    def _resolve_nprobe(self, n_centroids: int, topk: int) -> int:
        if self._index_nprobe_arg is not None:
            nprobe = int(self._index_nprobe_arg)
        else:
            nprobe = _env_int("TMR_GALLERY_INDEX_NPROBE", 0)
        if nprobe <= 0:
            # auto: 2*ceil(sqrt(C)) — measured (scripts/gallery_bench
            # --sweep) as the smallest policy holding selection recall
            # >= 0.9 at catalog scale with candidates ~N^(3/4) — but
            # never fewer buckets than the election needs winners: at
            # topk ~ C every bucket plausibly holds one, so small
            # banks degrade toward the full probe (the min_n gate is
            # what keeps genuinely small banks on the linear arm)
            nprobe = max(2 * int(np.ceil(np.sqrt(max(n_centroids, 1)))),
                         min(n_centroids, topk))
        return max(1, min(nprobe, n_centroids))

    def _ensure_index(self) -> SketchIndex:
        """The bank's SketchIndex, created lazily on the first indexed
        query (the off path never pays for it) and seeded with every
        registered entry; register/evict keep it in sync after that."""
        with self._lock:
            if self._index is None:
                frac = (float(self._index_rebuild_arg)
                        if self._index_rebuild_arg is not None
                        else _env_float("TMR_GALLERY_INDEX_REBUILD", 0.25))
                idx = SketchIndex(rebuild_frac=frac)
                for e in self._entries.values():
                    idx.add(e.name, entry_sketch(e.exemplars, e.k_real))
                self._index = idx
            return self._index

    def _prefilter_select(self, feats, groups: List[_Group], topk: int,
                          jnp) -> Tuple[set, Dict[str, float]]:
        """Elect the top-k entries for the full match. The index path
        (sublinear: medoid probe + candidate rescore) is a candidate
        OPTIMIZATION over the exact linear scan — any failure falls
        back to the scan, counted, never silent and never a lost
        frame."""
        total = sum(g.n_real for g in groups)
        if self._index_enabled() and total >= self._index_min_n_resolved():
            try:
                return self._index_select(feats, groups, topk, jnp)
            except Exception:
                self._m["index_fallbacks"].inc()
        return self._linear_select(feats, groups, topk)

    def _linear_select(self, feats, groups: List[_Group], topk: int
                       ) -> Tuple[set, Dict[str, float]]:
        """Today's exact scan: the per-group prefilter device calls are
        UNCHANGED (the ``TMR_GALLERY_INDEX=0`` bitwise contract); only
        the host-side ranking moved from a full O(N log N) sort to
        O(N) argpartition with identical tie semantics."""
        names: List[str] = []
        chunks: List[np.ndarray] = []
        for g in groups:
            fn = self._pred._get_gallery_prefilter_fn(g.n_bucket,
                                                      g.k_bucket)
            s = np.asarray(fn(feats, g.ex_dev, g.k_dev, g.n_dev))
            names.extend(g.names)
            chunks.append(s[:g.n_real])
        flat = np.concatenate(chunks)
        sel_idx = _topk_flat(flat, topk)
        selected = {names[i] for i in sel_idx}
        if flat.shape[0] <= _SCORE_TAIL_MAX:
            scores = {names[i]: float(flat[i])
                      for i in range(flat.shape[0])}
        else:
            scores = {names[i]: float(flat[i]) for i in sel_idx}
        return selected, scores

    def _index_select(self, feats, groups: List[_Group], topk: int, jnp
                      ) -> Tuple[set, Dict[str, float]]:
        """The coarse-to-fine indexed election: ONE batched device call
        scores every cluster's probe entries (medoid + anti-medoid),
        the best ``nprobe`` buckets by probe-MAX are rescored with the
        exact sketch correlation, and the same top-k/tie selection runs
        over those candidates only — device prefilter work drops from
        O(N) to O(sqrt(N) + nprobe * N/sqrt(N)) per frame."""
        idx = self._ensure_index()
        if idx.needs_rebuild():
            # racing searches may both recluster — benign (the rebuild
            # is deterministic and idempotent), and both are counted
            stamp = idx.rebuild()
            self._m["index_rebuilds"].inc()
            self._g_rebuild_wall.set(stamp["wall_s"])
        snap = idx.snapshot()
        if not snap["built"] or not snap["probes"]:
            raise RuntimeError("sketch index has no built clustering")
        with self._lock:
            entries = dict(self._entries)
        # flat (group-order, member-order) positions — the tie-break
        # order the linear scan's selection uses; also the membership
        # filter that keeps a stale index from ever returning an entry
        # not in the live registry view this search is serving
        pos: Dict[str, int] = {}
        for g in groups:
            for nm in g.names:
                pos[nm] = len(pos)
        spans: List[Tuple[int, int]] = []  # (start, len) per cluster
        pnames: List[str] = []
        for plist in snap["probes"]:
            spans.append((len(pnames), len(plist)))
            pnames.extend(plist)
        if any(nm not in entries for nm in pnames):
            raise RuntimeError("index probes out of sync with registry")
        self._m["index_queries"].inc()
        kpad = max(g.k_bucket for g in groups)
        pscores = self._score_flat(feats, [entries[nm] for nm in pnames],
                                   kpad, jnp)
        bucket_scores = np.asarray(
            [pscores[s:s + ln].max() for s, ln in spans], np.float32
        )
        probe = _topk_flat(bucket_scores,
                           self._resolve_nprobe(len(spans), topk))
        self._m["index_probes"].inc(int(probe.size))
        cand_set = set()
        for ci in probe:
            for nm in snap["members"][int(ci)]:
                if nm in pos and nm in entries:
                    cand_set.add(nm)
        if not cand_set:
            raise RuntimeError("index probe produced no candidates")
        cand = sorted(cand_set, key=pos.__getitem__)
        self._m["index_candidates"].inc(len(cand))
        cscores = self._score_flat(feats, [entries[nm] for nm in cand],
                                   kpad, jnp)
        sel_local = _topk_flat(cscores, topk)
        selected = {cand[i] for i in sel_local}
        if len(cand) <= _SCORE_TAIL_MAX:
            scores = {cand[i]: float(cscores[i])
                      for i in range(len(cand))}
        else:
            scores = {cand[i]: float(cscores[i]) for i in sel_local}
        hits = sum(
            1 for ci in probe
            if any(nm in selected for nm in snap["members"][int(ci)])
        )
        self._m["index_hits"].inc(hits)
        return selected, scores

    def _score_flat(self, feats, ents: List[GalleryEntry], kpad: int,
                    jnp) -> np.ndarray:
        """Exact coarse-sketch scores for an arbitrary entry list in
        ONE batched query shape-family: entries pad on the k axis to
        the bank-wide ``kpad`` (k_real masks the pad rows) and chunk at
        ``_INDEX_CHUNK`` per device call on power-of-two rungs, so the
        compile cache sees a handful of (rung, kpad) keys regardless
        of N or which buckets a probe elects."""
        out = np.empty((len(ents),), np.float32)
        done = 0
        while done < len(ents):
            chunk = ents[done:done + _INDEX_CHUNK]
            m = len(chunk)
            rung = 1
            while rung < m:
                rung *= 2
            ex = np.stack([
                e.exemplars if e.exemplars.shape[0] == kpad else
                np.concatenate(
                    [e.exemplars,
                     np.tile(e.exemplars[-1:],
                             (kpad - e.exemplars.shape[0], 1))],
                    axis=0,
                )
                for e in chunk
            ], axis=0)
            kr = np.asarray([e.k_real for e in chunk], np.int32)
            if rung > m:
                ex = np.concatenate(
                    [ex, np.tile(ex[-1:], (rung - m, 1, 1))], axis=0
                )
                kr = np.concatenate([kr, np.ones((rung - m,), np.int32)])
            fn = self._pred._get_gallery_prefilter_fn(rung, kpad)
            s = np.asarray(fn(feats, jnp.asarray(ex), jnp.asarray(kr),
                              jnp.asarray(m, jnp.int32)))
            out[done:done + m] = s[:m]
            done += m
        return out

    def search(self, image, prefilter_topk: Optional[int] = None
               ) -> Dict[str, dict]:
        """Match every registered pattern against ONE frame. Returns
        ``{name: dets}`` — numpy fixed-slot detections with leading dim
        1 per entry (``count`` included under the device decode tail).
        Entries the prefilter skipped return empty detections carrying
        ``degrade_steps: ["prefilter"]``; with the prefilter off (the
        default) results are exact — bitwise the N-loop of
        ``predict_multi_exemplar`` on cold frames, the documented
        heads-path allclose on feature-cache hits."""
        import jax.numpy as jnp

        img = np.asarray(image, np.float32)
        if img.ndim == 4 and img.shape[0] == 1:
            img = img[0]
        if img.ndim != 3 or img.shape[0] != img.shape[1] \
                or img.shape[2] != 3:
            raise ValueError(
                f"expected one square (S, S, 3) frame, got {img.shape}"
            )
        size = int(img.shape[0])
        if size != self.image_size:
            raise ValueError(
                f"frame size {size} != bank size {self.image_size} "
                "(a bank's capacity buckets are pinned to one grid; "
                "build a second bank for a second stream geometry)"
            )
        groups = self._groups_locked()
        total = sum(g.n_real for g in groups)
        if total == 0:
            return {}
        self._m["searches"].inc()
        topk = self._resolve_topk(prefilter_topk)
        prefilter_on = 0 < topk < total
        digest = array_digest(img)
        feats = (self.feature_cache.get(self._feature_key(digest, size))
                 if self.feature_cache.capacity > 0 else None)

        if feats is None and not prefilter_on and len(groups) == 1 \
                and (digest, size) not in self._seen:
            g = groups[0]
            if gallery_fused_ok(self._pred, g.capacity, g.n_bucket,
                                g.k_bucket):
                # cold frame, one bucket: the FUSED bitwise arm
                self._seen.put((digest, size), True)
                try:
                    fn = self._pred._get_gallery_fn(
                        g.capacity, g.n_bucket, g.k_bucket
                    )
                    dets = fn(
                        self._pred.exec_params(),
                        self._pred.refiner_params,
                        jnp.asarray(img[None]), g.ex_dev, g.k_dev,
                        g.n_dev,
                    )
                except Exception:
                    return self._nloop_fallback(img, groups)
                self._m["fused_frames"].inc()
                self._m["full_match_entries"].inc(g.n_real)
                return self._unpack(g, dets)

        # ---- features route: backbone program once, gallery tails on it
        computed = False
        if feats is None:
            try:
                bb = self._pred._get_backbone_fn()
                feats = bb(self._pred.exec_params(), jnp.asarray(img[None]))
            except Exception:
                return self._nloop_fallback(img, groups)
            computed = True
            self._m["backbone_fills"].inc()
        if computed and self.feature_cache.capacity > 0:
            # second-sighting promotion, as-is from the serve engine:
            # one-off frames never churn the cache, repeats amortize
            if (digest, size) in self._seen:
                self.feature_cache.put(self._feature_key(digest, size),
                                       feats)
            else:
                self._seen.put((digest, size), True)

        selected: Optional[set] = None
        scores: Dict[str, float] = {}
        if prefilter_on:
            self._m["prefilter_runs"].inc()
            selected, scores = self._prefilter_select(feats, groups,
                                                      topk, jnp)

        results: Dict[str, dict] = {}
        ran_heads = False
        for g in groups:
            if selected is None:
                keep = list(range(g.n_real))
            else:
                keep = [i for i in range(g.n_real)
                        if g.names[i] in selected]
            skipped = ([] if selected is None else
                       [i for i in range(g.n_real) if i not in set(keep)])
            if keep:
                try:
                    dets = self._run_group_heads(g, feats, keep, jnp)
                except Exception:
                    return self._nloop_fallback(img, groups)
                ran_heads = True
                self._m["full_match_entries"].inc(len(keep))
                results.update(self._unpack(g, dets, keep=keep))
            for i in skipped:
                results[g.names[i]] = self._empty_result(
                    scores.get(g.names[i])
                )
                self._m["prefilter_skipped"].inc()
        if ran_heads:
            # once per FRAME, not per bucket group: the counter
            # vocabulary (fused_frames / heads_frames /
            # nloop_fallback_frames) reconciles against `searches`
            self._m["heads_frames"].inc()
        return results

    def _run_group_heads(self, g: _Group, feats, keep: List[int], jnp):
        """Full match+decode for ``keep``'s entries of one group on the
        precomputed frame features, padded to the smallest rung that
        holds them (ragged selections inside a rung share the compiled
        program — the n_real mask does the rest)."""
        ladder = tuple(self._pred.N_BUCKETS)
        if len(keep) == g.n_real:
            ex_dev, k_dev, n_dev = g.ex_dev, g.k_dev, g.n_dev
            rung = g.n_bucket
        else:
            rung = int(next(
                (b for b in ladder if b >= len(keep)), len(keep)
            ))
            ex = g.host_ex[keep]
            kr = g.host_k[keep]
            pad = rung - len(keep)
            if pad:
                ex = np.concatenate(
                    [ex, np.tile(ex[-1:], (pad, 1, 1))], axis=0
                )
                kr = np.concatenate([kr, np.ones((pad,), np.int32)])
            ex_dev = jnp.asarray(ex)
            k_dev = jnp.asarray(kr)
            n_dev = jnp.asarray(len(keep), jnp.int32)
        fn = self._pred._get_gallery_heads_fn(
            g.capacity, rung, g.k_bucket, self.image_size
        )
        return fn(self._pred.exec_params(), self._pred.refiner_params,
                  feats, ex_dev, k_dev, n_dev)

    def _nloop_fallback(self, img: np.ndarray, groups: List[_Group]
                        ) -> Dict[str, dict]:
        """Exact per-entry fallback (the engine's isolation move): one
        ``predict_multi_exemplar`` call per entry. Correctness
        preserved, amortization lost — counted, never silent."""
        self._m["nloop_fallback_frames"].inc()
        results: Dict[str, dict] = {}
        for g in groups:
            for i in range(g.n_real):
                dets = self._pred.predict_multi_exemplar(
                    img[None], g.host_ex[i], k_real=int(g.host_k[i])
                )
                results[g.names[i]] = {
                    name: np.asarray(dets[name])
                    for name in _DET_FIELDS if name in dets
                }
            self._m["full_match_entries"].inc(g.n_real)
        return results

    def _unpack(self, g: _Group, dets: dict,
                keep: Optional[List[int]] = None) -> Dict[str, dict]:
        host = {name: np.asarray(dets[name])
                for name in _DET_FIELDS if name in dets}
        names = g.names if keep is None else [g.names[i] for i in keep]
        out: Dict[str, dict] = {}
        for row, name in enumerate(names):
            # .copy(): a row-slice VIEW would pin the whole padded
            # (n_bucket, slots, ...) batch alive per entry (the engine
            # _finish retention lesson)
            out[name] = {
                field: host[field][row:row + 1].copy() for field in host
            }
        return out

    def _empty_result(self, score: Optional[float]) -> dict:
        out = {
            "boxes": np.zeros((1, 0, 4), np.float32),
            "scores": np.zeros((1, 0), np.float32),
            "refs": np.zeros((1, 0, 2), np.float32),
            "valid": np.zeros((1, 0), bool),
            "degrade_steps": ["prefilter"],
        }
        if score is not None:
            out["prefilter_score"] = score
        return out

    # --------------------------------------------------------------- stats
    @property
    def counters(self) -> Dict[str, int]:
        return {name: c.value for name, c in self._m.items()}

    def index_stats(self) -> dict:
        """The sketch index's state + derived query metrics — light
        enough for fleet heartbeats (no group rebuild, no device
        placement, unlike ``stats``)."""
        with self._lock:
            idx = self._index
        probes = self._m["index_probes"].value
        hits = self._m["index_hits"].value
        out = {
            "enabled": self._index_enabled(),
            "min_n": self._index_min_n_resolved(),
            "queries": self._m["index_queries"].value,
            "hit_rate": (round(hits / probes, 4) if probes else None),
            "rebuild_wall_s": self._g_rebuild_wall.value,
            "built": False,
        }
        if idx is not None:
            out.update(idx.stats())
        return out

    def index_stamps(self) -> List[dict]:
        """The journaled rebuild-stamp log (empty before the first
        indexed query builds the index)."""
        with self._lock:
            idx = self._index
        return [] if idx is None else idx.stamps()

    def stats(self) -> dict:
        groups = self._groups_locked()
        return {
            "image_size": self.image_size,
            "entries": len(self),
            "groups": [
                {"capacity": g.capacity, "k_bucket": g.k_bucket,
                 "n_real": g.n_real, "n_bucket": g.n_bucket}
                for g in groups
            ],
            "max_n_bucket": self.max_n_bucket,
            "prefilter_topk": self._resolve_topk(None),
            "feature_cache": self.feature_cache.stats(),
            "index": self.index_stats(),
            **self.counters,
        }


# ------------------------------------------------------------ feature sink
class _SinkHandler(socketserver.StreamRequestHandler):
    """One worker's data-link connection: JSON lines in, acks out (the
    feature op is pipelined — see FeatureSinkServer)."""

    def handle(self):  # noqa: D102 — protocol loop
        state = {"features": 0, "errors": 0}
        while True:
            try:
                doc = _recv_line(self.rfile)
            except (ValueError, OSError):
                # a peer dying MID-WRITE leaves a truncated line
                # (ValueError) or a reset socket (OSError): that is a
                # LINK error — counted on the sink, never raised out
                # of the handler and never an infinite readline spin
                self.server.sink._count_link_error()
                break
            if doc is None:
                break
            try:
                reply = self.server.sink._dispatch(doc, state)
            except Exception:
                break
            if reply is not None:
                try:
                    _send_line(self.connection, reply)
                except OSError:
                    break
            if doc.get("op") == "bye":
                break


def _recv_line(f):
    from tmr_tpu.parallel.leases import recv_line

    return recv_line(f)


def _send_line(sock, doc):
    from tmr_tpu.parallel.leases import send_line

    send_line(sock, doc)


class _SinkServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FeatureSinkServer:
    """Serve-side feature sink: elastic map workers stream extracted
    features STRAIGHT into a feature index over the fleet data-link
    JSON-lines protocol instead of bouncing through ``.npy`` trees —
    the deferred half of PR 10's elastic item
    (``parallel/elastic.make_feature_sinks`` grows the matching
    ``tcp://host:port`` client).

    Protocol (one JSON document per line, ``serve.fleet.pack_array``
    payloads):

    - ``{"op": "hello", "worker": id}`` → ``{"ok": true}``;
    - ``{"op": "feature", "shard": s, "name": n, "array": ...}`` →
      NO reply (pipelined: TCP ordering means the next sync ack vouches
      for every feature sent before it);
    - ``{"op": "sync", "shard": s}`` → ``{"ok": <no errors since the
      last sync on this connection>, "features": n, "errors": e}`` —
      the ``atomic_save_npy`` durability contract on the wire: the
      worker's journal marker commits only after a clean ack, and a
      dirty ack fails the shard attempt so the retry machinery
      re-streams it;
    - ``{"op": "evict", "shard": s}`` → ack; drops the shard's features
      (the coordinator's quarantine-cleanup authority);
    - ``{"op": "bye"}`` → ack, connection closes.

    ANY successful round-trip — sync, evict, hello, an ``on_request``
    op — RESETS the connection's accounting window, so a historic
    error fails exactly the attempt that streamed it, never every
    attempt after (the retry machinery re-streams the whole shard).
    The pre-PR-16 server reset only on sync acks, which made an online
    (request/response, never-syncing) link accumulate errors forever.

    ``index`` is any :class:`LRUCache`-shaped store keyed
    ``(shard_stem, image_stem)`` — byte-bound it for HBM/host residency
    (``max_bytes``); a :class:`GalleryBank`'s feature cache or a plain
    standalone index both work. ``on_feature(shard, name, array)`` is
    the optional push hook (e.g. device placement, digest-keyed serve
    cache fill). ``on_request(doc, state)`` generalizes the sink into
    an ONLINE request/response link: ops the built-in table does not
    know route to it and its reply document (must carry ``"ok"``) is
    sent back on the same connection — serve/feature_tier.py's data
    plane composes this. Returning None falls through to the
    unknown-op error; an exception becomes a counted error reply.
    Backpressure is the CALLER's side of the contract: a client keeps
    a bounded in-flight window and fails fast (→ its own local
    fallback) instead of queueing unboundedly on the link.
    """

    def __init__(self, index: Optional[LRUCache] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 max_entries: int = 4096,
                 max_bytes: Optional[int] = None,
                 on_feature=None, on_request=None):
        self.index = LRUCache(max_entries, max_bytes=max_bytes) \
            if index is None else index
        self._on_feature = on_feature
        self._on_request = on_request
        self._lock = threading.Lock()
        self._host, self._port = host, int(port)
        self._server: Optional[_SinkServer] = None
        self._thread: Optional[threading.Thread] = None
        self._shards: Dict[str, set] = {}
        self._counters = {"connections": 0, "features": 0, "bytes": 0,
                          "syncs": 0, "evicted_shards": 0, "errors": 0,
                          "link_errors": 0}

    def start(self) -> Tuple[str, int]:
        with self._lock:
            if self._server is not None:
                return self._server.server_address
            server = _SinkServer((self._host, self._port), _SinkHandler)
            server.sink = self
            thread = threading.Thread(
                target=server.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="feature-sink", daemon=True,
            )
            self._server = server
            self._thread = thread
        thread.start()
        return server.server_address

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            if self._server is None:
                raise RuntimeError("feature sink not started")
            return self._server.server_address

    @property
    def url(self) -> str:
        host, port = self.address
        return f"tcp://{host}:{port}"

    def close(self) -> None:
        with self._lock:
            server, self._server = self._server, None
            thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def _count_link_error(self) -> None:
        """A connection died mid-line (truncated frame / reset peer) —
        the handler's loop exit path, counted here so wire-level peer
        death is observable without parsing logs."""
        with self._lock:
            self._counters["link_errors"] += 1

    # ------------------------------------------------------------ protocol
    @staticmethod
    def _ack(state: dict, reply: dict) -> dict:
        """A SUCCESSFUL round-trip closes the connection's accounting
        window (features/errors reset): the next attempt on the same
        connection starts clean. An unsuccessful reply leaves the
        window open — the error it reports is still this attempt's."""
        if reply.get("ok") is True:
            state["features"] = 0
            state["errors"] = 0
        return reply

    def _dispatch(self, doc: dict, state: dict) -> Optional[dict]:
        op = doc.get("op")
        if op == "feature":
            try:
                from tmr_tpu.serve.fleet import unpack_array

                shard = str(doc.get("shard", ""))
                name = str(doc.get("name", ""))
                arr = unpack_array(doc["array"])
                if self._on_feature is not None:
                    self._on_feature(shard, name, arr)
                self.index.put((shard, name), arr)
                state["features"] += 1
                with self._lock:
                    self._counters["features"] += 1
                    self._counters["bytes"] += int(arr.nbytes)
                    self._shards.setdefault(shard, set()).add(name)
            except Exception:
                state["errors"] += 1
                with self._lock:
                    self._counters["errors"] += 1
            return None  # pipelined: the sync ack vouches
        if op == "sync":
            with self._lock:
                self._counters["syncs"] += 1
            reply = {"op": "sync", "ok": state["errors"] == 0,
                     "shard": doc.get("shard"),
                     "features": state["features"],
                     "errors": state["errors"]}
            # a sync ack closes the window even when it reports dirty:
            # the errors it carries fail THIS shard attempt; the retry
            # re-streams the whole shard on a clean slate
            state["features"] = 0
            state["errors"] = 0
            return reply
        if op == "evict":
            shard = str(doc.get("shard", ""))
            with self._lock:
                names = self._shards.pop(shard, set())
                self._counters["evicted_shards"] += 1
            for name in names:
                self.index.pop((shard, name))
            return self._ack(state, {"op": "evict", "ok": True,
                                     "shard": shard,
                                     "dropped": len(names)})
        if op == "hello":
            with self._lock:
                self._counters["connections"] += 1
            return self._ack(state, {"op": "hello", "ok": True})
        if op == "bye":
            return self._ack(state, {"op": "bye", "ok": True})
        if self._on_request is not None:
            # online request/response generalization: unknown ops route
            # to the composing server (feature-tier data plane); its
            # successful replies close the window like any other ack
            try:
                reply = self._on_request(doc, state)
            except Exception as e:
                state["errors"] += 1
                with self._lock:
                    self._counters["errors"] += 1
                return {"op": op, "ok": False,
                        "error": f"{type(e).__name__}: {e}"}
            if reply is not None:
                return self._ack(state, reply)
        return {"ok": False, "error": f"unknown op {op!r}"}
