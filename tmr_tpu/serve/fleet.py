"""Elastic serve fleet: serve workers lease traffic partitions from a
generic lease service, and a front door routes requests to the current
holder with exactly-once result accounting.

PR 10 made the MAP phase elastic; serving — the millions-of-users path
in the ROADMAP north star — still died with its single process. This
module puts serving on the same lease discipline
(:class:`~tmr_tpu.parallel.leases.LeaseService`, the PR 10 coordinator's
state machine extracted):

- **traffic partitions** (image-size bucket × priority class) are the
  leased resources. Each :class:`FleetWorker` wraps a full serve engine
  (a real mesh-aware ``ServeEngine`` in production, the numpy
  :func:`stub_predictor` in drills), joins the fleet over the same
  JSON-lines control protocol the map workers use, leases partitions,
  and heartbeats them — one ``beat`` op carries every held lease plus
  the worker's measured drain rate and queue depth;
- the **front door** (:class:`ServeFleet`) owns submit: requests route
  to their partition's current lease holder over a per-worker data
  connection (``fleet.route`` fault point). A partition with no holder
  parks its requests; the grant flushes them;
- **exactly-once accounting**: every result commits at the front door
  (``fleet.commit`` fault point) against the in-flight registry AND the
  partition's CURRENT epoch — a revoked holder's late result is fenced
  (counted ``fenced_results``), a result for an already-terminal
  request counted ``late_results``, and a request id can never resolve
  twice (``double_served`` is the structural-zero witness). The
  reconciliation ``offered == completed + rejected + shed + errors`` is
  EXACT, engine-side and probe-side (the LeasedJournal discipline
  applied to serving);
- **death rebalance**: a worker kill -9 drops its control connection →
  its partitions reassign under epoch+1 (``worker_exit``; a SIGSTOP
  past the TTL reassigns as ``stale_heartbeat``) and their in-flight
  requests are RE-SUBMITTED to the new holder — or terminally rejected
  with structured cause ``worker_lost`` past ``TMR_FLEET_MAX_RESUBMITS``
  — never double-served, never silently dropped;
- **cluster-wide admission**: the front door's
  :class:`~tmr_tpu.serve.admission.AdmissionController` consumes the
  fleet's summed per-worker drain rates through
  ``attach_drain_source`` — ``retry_after_s`` reflects FLEET capacity,
  and beats that go stale stop counting (the controller falls back to
  its release window);
- **recruitment before degradation**: sustained queue saturation across
  the fleet asks the ``spawner`` for a new worker (``fleet.recruit``
  fault point) BEFORE the degrade ladder sees an anomaly — scale-out is
  the first response to load, result-shrinking the last (only when the
  fleet is already at ``TMR_FLEET_MAX_WORKERS`` does saturation reach
  the :class:`~tmr_tpu.serve.degrade.DegradeController`). A new worker
  joining an all-leased fleet triggers a ``scale_out`` rebalance so it
  actually absorbs load.

Proof: ``scripts/elastic_serve_probe.py`` (kill -9 mid-batch, SIGSTOP
past the TTL into a fenced late result, a recruitment round absorbing a
3× spike with the ladder at level 0) emits one validated
``elastic_serve_report/v1`` and rides tier-1 as a lean smoke.

Env knobs (lazily read; registered in config.ENV_KNOBS): the
``TMR_ELASTIC_*`` lease-liveness family (shared with the map client)
plus ``TMR_FLEET_SATURATION_PENDING``, ``TMR_FLEET_RECRUIT_PASSES``,
``TMR_FLEET_RECRUIT_GRACE``, ``TMR_FLEET_MAX_WORKERS``,
``TMR_FLEET_MAX_RESUBMITS``, ``TMR_FLEET_CHECK_S``.
"""

from __future__ import annotations

import base64
import math
import os
import socket
import socketserver
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tmr_tpu import obs
from tmr_tpu.obs import fleetobs as _fleetobs
from tmr_tpu.parallel.leases import (
    LeasePolicy,
    LeaseService,
    Resource,
    connect_timeout,
    oneshot,
    recv_line,
    send_line,
)
from tmr_tpu.serve.admission import AdmissionController, RejectedError
from tmr_tpu.serve.degrade import DegradeController
from tmr_tpu.utils import faults

#: detection fields the data plane ships (mirrors engine._DET_FIELDS +
#: the device tail's optional count vector)
_DET_FIELDS = ("boxes", "scores", "refs", "valid", "count")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ----------------------------------------------------------- wire helpers
def pack_array(a) -> dict:
    arr = np.ascontiguousarray(np.asarray(a))
    return {
        "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
    }


def unpack_array(doc: dict) -> np.ndarray:
    raw = base64.b64decode(doc["b64"])
    return np.frombuffer(raw, dtype=np.dtype(doc["dtype"])).reshape(
        doc["shape"]
    ).copy()


def pack_detections(dets: dict) -> dict:
    return {
        name: pack_array(dets[name]) for name in _DET_FIELDS
        if name in dets
    }


def unpack_detections(doc: dict) -> dict:
    return {name: unpack_array(rec) for name, rec in doc.items()}


# ------------------------------------------------------------- partitions
class FleetPartition(Resource):
    """One traffic partition: an image-size bucket × a priority class.
    Leased for the lifetime of its holder (never settles)."""

    __slots__ = ("size", "klass")

    def __init__(self, index: int, size: int, klass: int):
        super().__init__(index, f"s{size}c{klass}")
        self.size = int(size)
        self.klass = int(klass)


def fleet_policy(policy: Optional[LeasePolicy] = None) -> LeasePolicy:
    """The fleet's lease policy: the shared TMR_ELASTIC_* liveness
    knobs, with straggler speculation OFF (a long-held partition is
    normal, not a straggler) and the reassignment bound effectively
    unbounded (partitions legitimately move many times over a fleet's
    life — quarantining one would blackhole its traffic)."""
    if policy is not None:
        return policy
    return LeasePolicy.from_env(
        straggler_factor=0.0,
        max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


# ------------------------------------------------------------ fleet server
class _FleetHandler(socketserver.StreamRequestHandler):
    """Control-plane handler (the elastic _Handler shape): JSON lines
    in/out; EOF on a worker's control channel with leases held is the
    kill -9 signature."""

    def handle(self):  # noqa: D102 — protocol loop
        fleet = self.server.fleet  # type: ignore[attr-defined]
        control_worker = None
        clean = False
        try:
            while True:
                try:
                    msg = recv_line(self.rfile)
                except (OSError, ValueError):
                    break
                if msg is None:
                    break
                if msg.get("op") == "hello":
                    control_worker = msg.get("worker")
                if msg.get("op") == "bye":
                    clean = True
                reply = fleet.dispatch(msg)
                try:
                    send_line(self.connection, reply)
                except OSError:
                    break
                if clean:
                    break
        finally:
            if control_worker is not None:
                fleet.control_closed(control_worker, clean=clean)


class _FleetServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class _Inflight:
    """One routed request's front-door state."""

    __slots__ = ("rid", "fut", "partition", "epoch", "payload",
                 "priority", "attempts", "t_submit", "deadline", "obs")

    def __init__(self, rid: str, fut: Future, partition: int,
                 payload: dict, priority: int,
                 deadline: Optional[float]):
        self.rid = rid
        self.fut = fut
        self.partition = partition
        self.epoch: Optional[int] = None  # set when routed
        self.payload = payload
        self.priority = priority
        self.attempts = 0
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.obs = None  # front-door root span when TMR_FLEET_OBS=1


class _WorkerLink:
    """One data-plane connection from the front door to a worker. The
    send lock serializes writers (router thread + flush paths); the
    fleet owns one reader thread per link."""

    def __init__(self, address: Tuple[str, int]):
        self.address = (address[0], int(address[1]))
        self.sock = socket.create_connection(
            self.address, timeout=connect_timeout(5.0)
        )
        self.sock.settimeout(None)  # reader blocks until EOF/close
        self.file = self.sock.makefile("rb")
        self._wlock = threading.Lock()
        self.dead = False

    def send(self, doc: dict) -> bool:
        with self._wlock:
            if self.dead:
                return False
            try:
                # serve.link: one request is about to hit this worker's
                # wire — an injected raise severs the link exactly like
                # a peer-reset OSError would (the routing layer's
                # dead-link handling owns what happens next)
                faults.fire("serve.link")
                send_line(self.sock, doc)
                return True
            except OSError:
                self.dead = True
                return False
            except Exception:
                if faults.active():
                    self.dead = True
                    return False
                raise

    def close(self) -> None:
        with self._wlock:
            self.dead = True
        # shutdown FIRST: the reader thread is blocked inside this
        # file's buffered readinto holding its internal lock — closing
        # the file object from here would deadlock on that lock, while
        # a socket shutdown unblocks the read with EOF and lets the
        # reader run the file down itself
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class ServeFleet:
    """The fleet front door + partition-lease coordinator in one
    process: workers join over the control socket; callers submit here.

    Lock order (outermost first): ``self._svc.lock`` →
    ``self._lock`` → ``self._events_cond`` — never take an earlier lock
    while holding a later one. Socket I/O happens under NO fleet lock
    (links have their own send locks)."""

    def __init__(self, sizes: Sequence[int], *, classes: int = 1,
                 policy: Optional[LeasePolicy] = None,
                 admission: Optional[AdmissionController] = None,
                 degrade: Optional[DegradeController] = None,
                 spawner: Optional[Callable[[int], Any]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_resubmits: Optional[int] = None,
                 saturation_pending: Optional[int] = None,
                 recruit_passes: Optional[int] = None,
                 recruit_grace: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 check_interval_s: Optional[float] = None):
        self.sizes = sorted({int(s) for s in sizes})
        if not self.sizes:
            raise ValueError("a fleet needs at least one size bucket")
        self.classes = max(int(classes), 1)
        partitions = [
            FleetPartition(i, size, klass)
            for i, (size, klass) in enumerate(
                (s, c) for s in self.sizes for c in range(self.classes)
            )
        ]
        self.policy = fleet_policy(policy)
        self._svc = LeaseService(
            partitions, self.policy,
            metrics_prefix="fleet", noun="partition",
            key_field="partition", on_transition=self._on_transition,
            history_bound=4096,  # indefinite serving: a flapping
            # worker must not grow the event history forever
        )
        self._partitions = partitions
        #: cluster-wide admission: the fleet's summed per-worker drain
        #: rate is the capacity signal behind every retry_after hint
        self._admission = AdmissionController() if admission is None \
            else admission
        self._admission.attach_drain_source(self._drain_total)
        #: fleet-level degrade ladder: sees saturation anomalies ONLY
        #: when recruitment cannot absorb the load (the scale-out-first
        #: contract)
        self._degrade = DegradeController() if degrade is None \
            else degrade
        self._spawner = spawner
        self._host, self._port = host, int(port)
        self._lock = threading.RLock()
        self._inflight: Dict[str, _Inflight] = {}
        self._parked: Dict[int, deque] = {
            p.index: deque() for p in partitions
        }
        #: the partition's CURRENT routable epoch (None while unheld) —
        #: the result-commit fence compares against THIS, so a revoked
        #: holder's late result can never commit
        self._partition_epoch: Dict[int, Optional[int]] = {
            p.index: None for p in partitions
        }
        self._counters: Dict[str, int] = {
            k: 0 for k in (
                "offered", "completed", "rejected", "shed", "errors",
                "resubmitted", "fenced_results", "late_results",
                "double_served", "commit_faults",
            )
        }
        self._reject_causes: Dict[str, int] = {}
        self._worker_addr: Dict[str, Tuple[str, int]] = {}
        self._worker_beat: Dict[str, Tuple[float, float, int]] = {}
        self._links: Dict[str, _WorkerLink] = {}
        self._revoked_at: Dict[int, float] = {}
        self._rebalance_lat: deque = deque(maxlen=256)
        self._events: deque = deque()
        self._events_cond = threading.Condition()
        self._recruit = {"rounds": 0, "spawned": 0,
                         "saturated_passes": 0, "grace": 0}
        self._degrade_max_seen = 0
        self._rid_seq = 0
        self._closed = False
        self._stop_event = threading.Event()
        self._server: Optional[_FleetServer] = None
        self._threads: List[threading.Thread] = []
        self._t0 = time.monotonic()
        self._max_resubmits = (
            _env_int("TMR_FLEET_MAX_RESUBMITS", 2)
            if max_resubmits is None else int(max_resubmits)
        )
        self._saturation_pending = (
            _env_int("TMR_FLEET_SATURATION_PENDING", 16)
            if saturation_pending is None else int(saturation_pending)
        )
        self._recruit_passes = max(
            _env_int("TMR_FLEET_RECRUIT_PASSES", 2)
            if recruit_passes is None else int(recruit_passes), 1,
        )
        self._recruit_grace = max(
            _env_int("TMR_FLEET_RECRUIT_GRACE", 10)
            if recruit_grace is None else int(recruit_grace), 0,
        )
        self._max_workers = max(
            _env_int("TMR_FLEET_MAX_WORKERS", 4)
            if max_workers is None else int(max_workers), 1,
        )
        self._check_s = (
            _env_float("TMR_FLEET_CHECK_S",
                       self.policy.check_interval_s)
            if check_interval_s is None else float(check_interval_s)
        )
        # fleet observability plane (TMR_FLEET_OBS): None when off —
        # every instrumented site below pays one `is None` check
        self._fleetobs: Optional[_fleetobs.FleetObs] = (
            _fleetobs.FleetObs(hb_interval_s=self.policy.hb_interval_s)
            if _fleetobs.fleet_obs_enabled() else None
        )
        #: the coordinator's current live-autotune election (see
        #: ``live_tune_pass``): None until one fires; pushed to workers
        #: over the lease protocol's beat replies (epoch-guarded so a
        #: re-delivered beat never re-applies an old election)
        self._live_election: Optional[dict] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        server = _FleetServer((self._host, self._port), _FleetHandler)
        server.fleet = self  # type: ignore[attr-defined]
        threads = [
            threading.Thread(target=server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             name="fleet-control", daemon=True),
            threading.Thread(target=self._router_loop,
                             name="fleet-router", daemon=True),
            threading.Thread(target=self._monitor_loop,
                             name="fleet-monitor", daemon=True),
        ]
        with self._lock:
            self._server = server
            self._threads = threads
        self._svc.restart_clock()
        for t in threads:
            t.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        with self._lock:
            assert self._server is not None, "fleet not started"
            return self._server.server_address[:2]

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting, terminally reject everything still in
        flight (structured ``shutdown`` sheds — the bounded-drain
        discipline), and tear down threads/links."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            leftovers = list(self._inflight.values())
            self._inflight.clear()
            for dq in self._parked.values():
                dq.clear()
        for rec in leftovers:
            self._record_cause("shutdown")
            self._terminal(rec, "shed", RejectedError(
                "shutdown", "fleet closed with the request unserved",
                priority=rec.priority,
            ), already_removed=True)
        self._stop_event.set()
        with self._events_cond:
            self._events_cond.notify_all()
        with self._lock:
            server = self._server
            links = list(self._links.values())
            threads = list(self._threads)
        if server is not None:
            server.shutdown()
            server.server_close()
        for link in links:
            link.close()
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- routing
    def partition_index(self, size: int, priority: int) -> int:
        """The partition a (image size, priority class) pair routes to:
        exact size bucket (else the smallest bucket that fits, else the
        largest), class capped at the fleet's class count."""
        klass = min(max(int(priority), 0), self.classes - 1)
        if size in self.sizes:
            s_idx = self.sizes.index(size)
        else:
            fits = [i for i, s in enumerate(self.sizes) if s >= size]
            s_idx = fits[0] if fits else len(self.sizes) - 1
        return s_idx * self.classes + klass

    def submit(self, image, exemplars, multi: bool = False,
               k_real: Optional[int] = None, priority: int = 0,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the
        detections dict (numpy). Admission applies HERE — cluster-wide:
        a bounced future carries a structured RejectedError whose
        retry_after reflects the fleet's summed drain rate."""
        fut: Future = Future()
        if self._closed:
            fut.set_exception(RuntimeError("fleet is closed"))
            return fut
        rej = self._admission.try_admit(priority)
        if rej is not None:
            self._record_cause(rej.cause)
            with self._lock:
                self._counters["offered"] += 1
                self._counters["rejected"] += 1
            fut.set_exception(rej)
            return fut
        try:
            image = np.asarray(image, np.float32)
            if image.ndim == 4 and image.shape[0] == 1:
                image = image[0]
            if image.ndim != 3 or image.shape[0] != image.shape[1] \
                    or image.shape[2] != 3:
                raise ValueError(
                    f"expected one square (S, S, 3) image, got "
                    f"{image.shape}"
                )
            ex = np.asarray(exemplars, np.float32).reshape(-1, 4)
            payload = {
                "op": "serve",
                "image": pack_array(image),
                "exemplars": pack_array(ex),
                "multi": bool(multi),
                "k_real": None if k_real is None else int(k_real),
                "priority": max(int(priority), 0),
                "deadline_ms": (None if deadline_ms is None
                                else float(deadline_ms)),
            }
        except Exception as e:  # isolation: reject this request alone
            self._admission.release_class(priority)
            with self._lock:
                self._counters["offered"] += 1
                self._counters["errors"] += 1
            fut.set_exception(e)
            return fut
        index = self.partition_index(int(image.shape[0]), priority)
        with self._lock:
            # authoritative closed check INSIDE the lock: a submit
            # racing close() must never enter the registry after the
            # drain emptied it (its future would hang forever)
            if self._closed:
                closed = True
            else:
                closed = False
                self._rid_seq += 1
                rid = f"r{self._rid_seq}"
                payload["rid"] = rid
                rec = _Inflight(
                    rid, fut, index, payload, max(int(priority), 0),
                    None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1000.0,
                )
                if self._fleetobs is not None:
                    # front door mints THE trace id: the root span is
                    # pre-minted so its id rides the wire while it is
                    # still open (closed in _terminal)
                    rec.obs = _fleetobs.root_span(
                        "fleet.submit", rid=rid, partition=index,
                    )
                    payload["ctx"] = rec.obs.ctx()
                self._counters["offered"] += 1
                self._inflight[rid] = rec
        if closed:
            self._admission.release_class(priority)
            fut.set_exception(RuntimeError("fleet is closed"))
            return fut
        self._push_event(("route", rid))
        return fut

    def predict(self, image, exemplars, **kw) -> dict:
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(image, exemplars, **kw).result()

    def _push_event(self, event: tuple) -> None:
        with self._events_cond:
            self._events.append(event)
            self._events_cond.notify()

    def _router_loop(self) -> None:
        while True:
            with self._events_cond:
                while not self._events and not self._stop_event.is_set():
                    self._events_cond.wait(timeout=0.5)
                if self._stop_event.is_set() and not self._events:
                    return
                event = self._events.popleft()
            try:
                self._handle_event(event)
            except Exception:
                # the router must survive anything: a request it could
                # not place stays parked for the next pass
                pass

    def _handle_event(self, event: tuple) -> None:
        kind = event[0]
        if kind == "route":
            self._route_one(event[1])
        elif kind == "granted":
            self._flush_partition(event[1])
        elif kind == "revoked":
            self._resubmit_partition(event[1], event[2])

    def _route_one(self, rid: str) -> None:
        """Send one in-flight request to its partition's current lease
        holder — or park it until a holder exists."""
        with self._lock:
            rec = self._inflight.get(rid)
        if rec is None:
            return
        holder = self._svc.holder(rec.partition)
        if holder is None:
            with self._lock:
                if rid in self._inflight:
                    self._parked[rec.partition].append(rid)
            return
        wid, epoch = holder
        link = self._link_for(wid)
        if link is None:
            with self._lock:
                if rid in self._inflight:
                    self._parked[rec.partition].append(rid)
            return
        try:
            # the route fault point (scope: partition index, epoch)
            # fires OUTSIDE every fleet lock — injected latency stalls
            # one routing decision, not the fleet
            with faults.shard_scope(rec.partition, epoch):
                faults.fire("fleet.route")
        except Exception:
            self._fail_attempt(rec, f"injected route fault for {rid}")
            return
        with self._lock:
            if rid not in self._inflight:
                return
            rec.epoch = epoch
            doc = dict(rec.payload)
            doc["partition"] = rec.partition
            doc["epoch"] = epoch
        if not link.send(doc):
            self._fail_attempt(rec, f"send to worker {wid!r} failed")

    def _fail_attempt(self, rec: _Inflight, message: str) -> None:
        """One routing/serving attempt failed. Bounded: past
        ``max_resubmits`` the request terminally rejects with cause
        ``worker_lost`` (never an unbounded silent retry loop)."""
        with self._lock:
            if rec.rid not in self._inflight:
                return
            rec.attempts += 1
            rec.epoch = None
            if rec.attempts > self._max_resubmits:
                del self._inflight[rec.rid]
                exceeded = True
            else:
                self._counters["resubmitted"] += 1
                self._parked[rec.partition].append(rec.rid)
                exceeded = False
        if exceeded:
            self._record_cause("worker_lost")
            self._terminal(rec, "rejected", RejectedError(
                "worker_lost",
                f"{message}; gave up after {rec.attempts} attempts",
                priority=rec.priority,
            ), already_removed=True)
        # else: parked above; the next grant flushes it

    def _flush_partition(self, index: int) -> None:
        """A (re)granted partition drains its parked requests to the
        new holder."""
        with self._lock:
            rids = list(self._parked.get(index, ()))
            self._parked[index].clear()
        for rid in rids:
            self._route_one(rid)

    def _resubmit_partition(self, index: int, epoch: int) -> None:
        """A revoked lease orphans its in-flight requests: every one
        routed under the dead epoch goes back through the bounded
        resubmission path."""
        with self._lock:
            orphans = [
                rec for rec in self._inflight.values()
                if rec.partition == index and rec.epoch == epoch
            ]
        for rec in orphans:
            self._fail_attempt(
                rec,
                f"partition {index} epoch {epoch} revoked mid-flight",
            )

    def _link_for(self, wid: str) -> Optional[_WorkerLink]:
        with self._lock:
            link = self._links.get(wid)
            addr = self._worker_addr.get(wid)
        if link is not None and not link.dead:
            return link
        if addr is None:
            return None
        try:
            link = _WorkerLink(addr)
        except OSError:
            return None
        reader = threading.Thread(
            target=self._reader_loop, args=(wid, link),
            name=f"fleet-reader-{wid}", daemon=True,
        )
        with self._lock:
            old = self._links.get(wid)
            self._links[wid] = link
        if old is not None:
            old.close()
        reader.start()  # daemon; exits on link EOF/close, never joined
        return link

    def _reader_loop(self, wid: str, link: _WorkerLink) -> None:
        """One data connection's results, committed as they arrive."""
        while True:
            try:
                doc = recv_line(link.file)
            except (OSError, ValueError):
                break
            if doc is None:
                break
            try:
                self._commit_result(doc)
            except Exception:
                pass  # a malformed line must not kill the reader
        link.dead = True
        self._link_lost(wid)

    def _link_lost(self, wid: str) -> None:
        """A DATA link died while its worker may still be alive (torn
        connection, malformed stream): the lease layer saw no failure,
        so revocation will never rescue the requests already in flight
        on that link — push them back through the bounded resubmission
        path ourselves (the control pass re-flushes once a fresh link
        dials; exactly-once holds because the registry, not the wire,
        is the commit authority)."""
        if self._stop_event.is_set():
            return
        held: List[Tuple[int, int]] = []
        with self._svc.lock:
            for part in self._partitions:
                for epoch, lease in part.leases.items():
                    if lease.worker == wid:
                        held.append((part.index, epoch))
        for index, epoch in held:
            self._push_event(("revoked", index, epoch))

    # ----------------------------------------------------------- committing
    def _commit_result(self, doc: dict) -> None:
        """Exactly-once result commit: the in-flight registry is the
        set of open requests, and the partition's CURRENT epoch is the
        fence — a revoked holder's late result never commits, a second
        result for a terminal request never resolves anything."""
        rid = str(doc.get("rid"))
        index = int(doc.get("partition", -1))
        epoch = int(doc.get("epoch", -1))
        worker = str(doc.get("worker", ""))
        try:
            with faults.shard_scope(index, epoch):
                faults.fire("fleet.commit")
        except Exception:
            # an injected commit fault discards the result and ends the
            # request terminally — a half-committed result must not
            # linger as phantom in-flight work
            with self._lock:
                rec = self._inflight.pop(rid, None)
                self._counters["commit_faults"] += 1
            if rec is not None:
                self._record_cause("worker_lost")
                self._terminal(rec, "rejected", RejectedError(
                    "worker_lost", "injected fault at fleet.commit",
                    priority=rec.priority,
                ), already_removed=True)
            return
        fence_op = None
        with self._lock:
            rec = self._inflight.get(rid)
            if rec is None:
                self._counters["late_results"] += 1
                return
            current = self._partition_epoch.get(index)
            if epoch != rec.epoch or current != epoch:
                # the epoch fence at the result commit (the
                # LeasedJournal discipline): a result from a revoked
                # lease is rejected BEFORE it can touch the future
                self._counters["fenced_results"] += 1
                fence_op = ("commit", index, worker, epoch)
            elif rec.fut.done():
                # structurally unreachable (terminal requests leave the
                # registry) — counted so the report can PROVE it
                self._counters["double_served"] += 1
                del self._inflight[rid]
                return
            else:
                del self._inflight[rid]
        if fence_op is not None:
            self._svc.record_fence(index, worker, epoch, "commit")
            return
        status = doc.get("status")
        if status == "ok":
            try:
                result = unpack_detections(doc.get("detections") or {})
            except Exception as e:
                self._terminal(rec, "errors", e, already_removed=True)
                return
            self._terminal(rec, "completed", result,
                           already_removed=True)
        elif status == "fenced":
            # the worker no longer held the lease at receipt: the
            # partition is mid-rebalance — bounded resubmission. A
            # fleet that closed in the window must NOT re-register the
            # request (close already drained the registry): it ends
            # terminally with the shutdown discipline instead.
            with self._lock:
                readd = not self._closed
                if readd:
                    self._inflight[rid] = rec  # back in the registry
            if readd:
                self._fail_attempt(
                    rec, f"worker {worker!r} fenced the request",
                )
            else:
                self._record_cause("shutdown")
                self._terminal(rec, "shed", RejectedError(
                    "shutdown",
                    "fleet closed while the request was mid-rebalance",
                    priority=rec.priority,
                ), already_removed=True)
        elif status == "rejected":
            cause = doc.get("cause") or "queue_full"
            err = RejectedError(
                cause if cause in ("queue_full", "class_limit",
                                   "rate_limited", "deadline",
                                   "shutdown", "worker_lost")
                else "queue_full",
                str(doc.get("message") or "worker rejected the request"),
                priority=rec.priority,
            )
            bucket = "shed" if err.cause in ("deadline", "shutdown") \
                else "rejected"
            self._record_cause(err.cause)
            self._terminal(rec, bucket, err, already_removed=True)
        else:
            self._terminal(rec, "errors", RuntimeError(
                str(doc.get("message") or f"worker error ({status})")
            ), already_removed=True)

    def _terminal(self, rec: _Inflight, bucket: str, outcome,
                  already_removed: bool = False) -> None:
        """One request's single terminal event: releases the admission
        slot, counts the outcome bucket, resolves the future."""
        with self._lock:
            if not already_removed and \
                    self._inflight.pop(rec.rid, None) is None:
                return
            self._counters[bucket] += 1
        self._admission.release_class(rec.priority)
        if bucket == "completed":
            if not rec.fut.done():
                rec.fut.set_result(outcome)
        elif not rec.fut.done():
            rec.fut.set_exception(outcome)
        if rec.obs is not None:
            rec.obs.close(outcome=bucket, attempts=rec.attempts)
        if obs.flight_enabled():
            obs.flight_record(
                "fleet.request", rid=rec.rid, outcome=bucket,
                partition=rec.partition, attempts=rec.attempts,
                latency_s=round(time.monotonic() - rec.t_submit, 6),
            )

    def _record_cause(self, cause: str) -> None:
        with self._lock:
            self._reject_causes[cause] = (
                self._reject_causes.get(cause, 0) + 1
            )

    # ----------------------------------------------------- control protocol
    def dispatch(self, msg: dict) -> dict:
        op = msg.get("op")
        handler = {
            "hello": self._op_hello,
            "lease": self._op_lease,
            "beat": self._op_beat,
            "fail": self._op_fail,
            "bye": self._op_bye,
            "state": lambda m: self.state(),
        }.get(op)
        if handler is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            return handler(msg)
        except Exception as e:  # protocol must answer, never wedge
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _op_hello(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        # a rejoining stable worker id is ALIVE again: without clearing
        # its departure flags, the control pass would strip its fresh
        # address/link every interval and its partitions' traffic would
        # park forever (drained stays sticky — poison drain survives a
        # reconnect)
        self._svc.rejoin(wid)
        data_addr = msg.get("data_addr")
        if isinstance(data_addr, (list, tuple)) and len(data_addr) == 2:
            with self._lock:
                self._worker_addr[wid] = (str(data_addr[0]),
                                          int(data_addr[1]))
        self._rebalance_for_join(wid)
        return {
            "ok": True,
            "sizes": list(self.sizes),
            "classes": self.classes,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
            "partitions": len(self._partitions),
        }

    def _op_lease(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        wait = {"partition": None,
                "wait_s": max(self.policy.check_interval_s, 0.05)}
        verdict, part, epoch = self._svc.select(wid)
        if verdict == "drained":
            return {"partition": None, "drained": True}
        if verdict != "grant":
            return wait  # fleets are never "done" while serving
        if self._svc.install(part, epoch, wid) is None:
            return wait
        return {
            "partition": part.key,
            "index": part.index,
            "epoch": epoch,
            "size": part.size,
            "klass": part.klass,
            "ttl_s": self.policy.lease_ttl_s,
            "hb_interval_s": self.policy.hb_interval_s,
        }

    def _op_beat(self, msg: dict) -> dict:
        """One worker heartbeat covering every lease it holds, plus its
        measured drain rate and queue depth — the cluster-wide
        admission signal rides the liveness beat."""
        wid = str(msg.get("worker"))
        stale: List[List[int]] = []
        for pair in msg.get("held") or ():
            index, epoch = int(pair[0]), int(pair[1])
            if not self._svc.heartbeat(wid, index, epoch):
                stale.append([index, epoch])
        drain = msg.get("drain")
        pending = msg.get("pending")
        with self._lock:
            self._worker_beat[wid] = (
                time.monotonic(),
                float(drain) if isinstance(drain, (int, float)) else 0.0,
                int(pending) if isinstance(pending, int) else 0,
            )
        worker = self._svc.worker_rec(wid)
        # the live-autotune election rides the beat reply (extra keys
        # are tolerated by every peer version); absent when none fired
        with self._lock:
            le = self._live_election
        extra = {"live_tune": dict(le)} if le is not None else {}
        fo = self._fleetobs
        if fo is not None:
            fo.note_beat(wid)
            att = msg.get("obs")
            if att is not None:
                fo.fold(wid, att)
            # the reply stamps OUR perf_counter so the worker can run
            # midpoint clock-offset estimation over this round-trip
            return {"ok": True, "stale": stale,
                    "drained": worker.drained,
                    "obs_ts": time.perf_counter(), **extra}
        return {"ok": True, "stale": stale, "drained": worker.drained,
                **extra}

    def _op_fail(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        index, epoch = int(msg.get("index", -1)), int(msg.get("epoch", -1))
        res = self._svc.fail(wid, index, epoch, msg.get("causes") or [])
        return {"ok": True, **res}

    def _op_bye(self, msg: dict) -> dict:
        wid = str(msg.get("worker"))
        fo = self._fleetobs
        if fo is not None and msg.get("obs") is not None:
            # end-of-life flush: a clean leaver's final registry totals
            # (+ flight/trace tail) land before its state disappears —
            # short-lived workers are not observability-invisible
            fo.fold(wid, msg.get("obs"), final=True)
        self._svc.bye(wid)
        # a clean leaver still releases its partitions for rebalance —
        # serve leases are held for the worker's lifetime, so a
        # graceful leave exits through the same worker_exit cause as a
        # crash (the closed vocabulary documents both)
        self._svc.revoke_worker(wid, "worker_exit")
        return {"ok": True}

    def control_closed(self, wid: str, clean: bool) -> None:
        self._svc.control_closed(str(wid), clean)

    # -------------------------------------------------------- lease events
    def _on_transition(self, part: FleetPartition, lease,
                       state: str) -> None:
        """LeaseService hook (fires under the service lock): keeps the
        commit fence's per-partition epoch EXACTLY in step with grants
        and revocations, and queues the router's flush/resubmit work."""
        if state == "held":
            with self._lock:
                self._partition_epoch[part.index] = lease.epoch
                revoked_at = self._revoked_at.pop(part.index, None)
                if revoked_at is not None:
                    self._rebalance_lat.append(
                        time.monotonic() - revoked_at
                    )
            self._push_event(("granted", part.index))
        elif state in ("revoked", "failed"):
            # a worker-reported failure frees the partition exactly
            # like a revocation: the fence epoch clears so nothing from
            # the failed holder can commit, and its in-flight requests
            # go back through the bounded resubmission path
            with self._lock:
                self._partition_epoch[part.index] = None
                self._revoked_at.setdefault(part.index,
                                            time.monotonic())
            self._push_event(("revoked", part.index, lease.epoch))

    def _rebalance_for_join(self, new_wid: str) -> None:
        """Scale-out rebalance: a new worker joining an all-leased
        fleet takes over the excess partitions of over-loaded holders
        (cause ``scale_out``) — recruitment must actually MOVE load,
        not just add an idle process."""
        excess: List[Tuple[int, int]] = []
        with self._svc.lock:
            alive = [
                w.wid for w in self._svc.workers.values()
                if not (w.drained or w.dead or w.bye)
            ]
            if len(alive) < 2 or self._svc.pending_snapshot():
                return
            target = math.ceil(len(self._partitions) / len(alive))
            held: Dict[str, List[Tuple[int, int]]] = {}
            for part in self._partitions:
                for epoch, lease in part.leases.items():
                    held.setdefault(lease.worker, []).append(
                        (part.index, epoch)
                    )
            for wid, leases in held.items():
                if wid == new_wid:
                    continue
                for index, epoch in leases[target:]:
                    excess.append((index, epoch))
        for index, epoch in excess:
            self._svc.revoke_lease(index, epoch, "scale_out")

    # -------------------------------------------------------- control loop
    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(self._check_s):
            try:
                self._control_pass()
            except Exception:
                pass  # the control loop must survive anything

    def _control_pass(self) -> None:
        """One fleet control pass: lease liveness, deadline expiry, the
        recruitment election, and (only when scale-out cannot help) the
        degrade ladder's anomaly feed."""
        self._svc.expire_pass()
        self._expire_deadlines()
        pending = self.pending()
        with self._svc.lock:
            alive = sum(
                1 for w in self._svc.workers.values()
                if not (w.drained or w.dead or w.bye)
            )
            departed = [
                w.wid for w in self._svc.workers.values()
                if w.dead or w.drained or w.bye
            ]
        # worker churn must not leak: a departed worker's beat/address/
        # link bookkeeping goes with it (the service keeps the
        # WorkerRecord itself — that is report history, bounded by
        # distinct worker ids, not by reconnects)
        dead_links: List[_WorkerLink] = []
        with self._lock:
            for wid in departed:
                self._worker_beat.pop(wid, None)
                self._worker_addr.pop(wid, None)
                link = self._links.pop(wid, None)
                if link is not None:
                    dead_links.append(link)
        for link in dead_links:
            link.close()
        can_recruit = (
            self._spawner is not None and alive < self._max_workers
        )
        with self._lock:
            saturated = pending > self._saturation_pending
            if saturated:
                self._recruit["saturated_passes"] += 1
            else:
                self._recruit["saturated_passes"] = 0
            in_grace = self._recruit["grace"] > 0
            if in_grace:
                self._recruit["grace"] -= 1
            should_recruit = (
                saturated and can_recruit and not in_grace
                and self._recruit["saturated_passes"]
                >= self._recruit_passes
            )
            if should_recruit:
                spawn_i = self._recruit["spawned"]
        if should_recruit:
            try:
                faults.fire("fleet.recruit")
            except Exception:
                should_recruit = False  # election vetoed; retry later
        if should_recruit:
            try:
                self._spawner(spawn_i)
            except Exception:
                should_recruit = False
        if should_recruit:
            with self._lock:
                self._recruit["rounds"] += 1
                self._recruit["spawned"] += 1
                self._recruit["saturated_passes"] = 0
                self._recruit["grace"] = self._recruit_grace
            obs.get_registry().counter("fleet.recruited").inc()
        # degradation is the LAST resort: saturation reaches the ladder
        # only when recruitment cannot absorb it (spawner exhausted or
        # absent) — a spike the fleet can scale out of must never
        # shrink user results
        if self._degrade.enabled:
            anomalies: List[dict] = []
            if saturated and not can_recruit and not should_recruit \
                    and not in_grace:
                anomalies = [{
                    "anomaly": "queue_saturation",
                    "message": f"fleet backlog {pending} over "
                               f"{self._saturation_pending} with "
                               "recruitment exhausted",
                    "evidence": {"pending": pending, "workers": alive},
                }]
            level = self._degrade.observe(anomalies)
            with self._lock:
                self._degrade_max_seen = max(self._degrade_max_seen,
                                             level)
        # safety net: flush any parked work whose partition is held
        # (covers a grant event the router processed before the
        # worker's data server came up)
        for part in self._partitions:
            with self._lock:
                has_parked = bool(self._parked.get(part.index))
            if has_parked and self._svc.holder(part.index) is not None:
                self._push_event(("granted", part.index))

    def _expire_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [
                rec for rec in self._inflight.values()
                if rec.deadline is not None and now > rec.deadline
            ]
            for rec in expired:
                del self._inflight[rec.rid]
        for rec in expired:
            self._record_cause("deadline")
            self._terminal(rec, "shed", RejectedError(
                "deadline",
                f"deadline expired after "
                f"{(now - rec.t_submit) * 1000:.1f} ms in the fleet",
                priority=rec.priority,
            ), already_removed=True)

    # -------------------------------------------------------------- signals
    def _drain_total(self) -> float:
        """Summed per-worker drain rate from the recent beats — the
        admission controller's cluster-wide capacity signal. Beats
        older than ~3 heartbeat intervals stop counting (a dead
        worker's historic rate is not capacity), so a fully-stale fleet
        reads 0.0 and the controller falls back to its release
        window."""
        horizon = 3.0 * max(self.policy.hb_interval_s, 0.1)
        now = time.monotonic()
        with self._lock:
            return sum(
                rate for (t, rate, _pending)
                in self._worker_beat.values()
                if now - t <= horizon
            )

    def pending(self) -> int:
        """The fleet backlog: every open request (routed or parked)
        plus the queue depth the workers reported on their RECENT
        beats — the queue-saturation signal. Beats past the same
        horizon the drain signal uses stop counting: a dead worker's
        last reported backlog must not read as permanent saturation
        (which would recruit to the ceiling, then degrade an idle
        fleet)."""
        horizon = 3.0 * max(self.policy.hb_interval_s, 0.1)
        now = time.monotonic()
        with self._lock:
            return len(self._inflight) + sum(
                p for (t, _rate, p) in self._worker_beat.values()
                if now - t <= horizon
            )

    # -------------------------------------------------------------- reports
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def state(self) -> dict:
        """Mid-run introspection (NOT the report)."""
        with self._svc.lock:
            with self._lock:
                out = {
                    "ok": True,
                    "partitions": {
                        p.key: {
                            "status": p.status,
                            "holder": self._svc.holder(p.index),
                            "epoch": self._partition_epoch.get(p.index),
                            "parked": len(self._parked.get(p.index, ())),
                        }
                        for p in self._partitions
                    },
                    "workers": {
                        w.wid: {"drained": w.drained, "dead": w.dead}
                        for w in self._svc.workers.values()
                    },
                    "inflight": len(self._inflight),
                    "counters": dict(self._counters),
                    "reassignments": [
                        dict(r) for r in self._svc.reassignments
                    ],
                }
        # outside every fleet lock (fleetobs locks are leaves, but the
        # merged rollup is not worth holding the routing locks for);
        # disabled state() stays byte-identical — no key at all
        if self._fleetobs is not None:
            out["fleet_metrics"] = self._fleetobs.state()
        return out

    @property
    def fleet_obs(self) -> Optional[_fleetobs.FleetObs]:
        """The coordinator-side observability plane (None when
        TMR_FLEET_OBS is off) — probes reach the stitched timeline and
        rollup through here."""
        return self._fleetobs

    def fleet_obs_pass(self) -> List[dict]:
        """One caller-driven fleet HealthWatch pass over the beat-
        merged registry (caller-driven — the monitor loop does NOT run
        passes on its own, so probes/operators control the window
        boundaries and at-most-once-per-pass firing is deterministic;
        run it on whatever cadence state() is polled on). Returns the
        anomalies fired this pass; [] when the plane is off."""
        fo = self._fleetobs
        if fo is None:
            return []
        with self._svc.lock:
            with self._lock:
                # beat_gap candidates are workers that have NOT cleanly
                # left — a kill -9 sets dead (dirty close) but its
                # silence is exactly what beat_gap must name, so only
                # bye/drained leavers are excluded
                live = [w.wid for w in self._svc.workers.values()
                        if not w.bye and not w.drained]
                held: Dict[str, list] = {}
                for p in self._partitions:
                    holder = self._svc.holder(p.index)
                    if holder:
                        held.setdefault(holder, []).append(p.key)
        return fo.run_pass(live=live, held=held)

    # -------------------------------------------------------- live autotune
    def live_tune_pass(self, knob: str, *,
                       wins_needed: Optional[int] = None,
                       geometry: str = "") -> Optional[dict]:
        """One caller-driven fleet-wide election pass for ``knob``:
        aggregate per-worker shadow-win/refusal counters from the
        beat-merged registry (each worker's LiveTuner counts its
        decisive wins into ``live_tune.win.<knob>=<arm>``, refusals into
        ``live_tune.refusal.<knob>=<arm>`` — they ride the beat
        attachments into ``state()["fleet_metrics"]`` with no new
        plumbing), consult the fleet watch for demote anomalies, and
        push the verdict to every worker over the next beat replies.

        Demotion outranks promotion: a recent :data:`DEMOTE_ANOMALIES
        <tmr_tpu.autotune_live.DEMOTE_ANOMALIES>`-kind fleet anomaly
        while an election stands revokes it (``winner: None``,
        ``demoted: True``, cause recorded) and disqualifies the demoted
        arm from later passes. Otherwise the non-refused, non-demoted
        arm whose summed wins reach ``wins_needed``
        (``TMR_LIVE_TUNE_WINS``) becomes the election. Every verdict
        bumps ``epoch`` so workers apply each at most once. Returns the
        current election doc (None when nothing has fired); requires
        the observability plane (TMR_FLEET_OBS) and TMR_LIVE_TUNE."""
        from tmr_tpu import autotune_live

        fo = self._fleetobs
        if fo is None or not autotune_live.live_tune_enabled():
            return None
        need = autotune_live.default_wins() if wins_needed is None \
            else max(int(wins_needed), 1)
        counters = fo.metrics.merged().get("counters") or {}
        win_prefix = f"live_tune.win.{knob}="
        ref_prefix = f"live_tune.refusal.{knob}="
        wins: Dict[str, int] = {}
        refused: set = set()
        for name, value in counters.items():
            if name.startswith(win_prefix):
                wins[name[len(win_prefix):]] = int(value)
            elif name.startswith(ref_prefix) and value:
                refused.add(name[len(ref_prefix):])
        demote_cause = None
        for rec in fo.watch.recent():
            if rec.get("anomaly") in autotune_live.DEMOTE_ANOMALIES:
                demote_cause = rec
                break
        with self._lock:
            le = self._live_election
            epoch = int(le["epoch"]) if le else 0
            demoted_arms = set((le or {}).get("demoted_arms") or ())
            standing = (le or {}).get("winner")
            if demote_cause is not None and standing:
                self._live_election = {
                    "knob": str(knob), "winner": None,
                    "demoted": True, "demoted_arm": standing,
                    "cause": demote_cause.get("anomaly"),
                    "evidence": dict(demote_cause.get("evidence") or {}),
                    "geometry": str(geometry),
                    "demoted_arms": sorted(demoted_arms | {standing}),
                    "epoch": epoch + 1,
                }
                return dict(self._live_election)
            best = None
            for arm, n in sorted(wins.items()):
                if arm in refused or arm in demoted_arms or n < need:
                    continue
                if best is None or n > wins[best]:
                    best = arm
            if best is not None and best != standing:
                self._live_election = {
                    "knob": str(knob), "winner": best,
                    "demoted": False, "wins": wins[best],
                    "geometry": str(geometry),
                    "demoted_arms": sorted(demoted_arms),
                    "epoch": epoch + 1,
                }
            return dict(self._live_election) if self._live_election \
                else None

    def report(self) -> dict:
        """The fleet section of an ``elastic_serve_report/v1`` (the
        probe embeds one per phase; diagnostics._validate_fleet_section
        checks it, including the exact accounting reconciliation)."""
        # admission stats FIRST, outside every fleet lock: the
        # controller's lock and this fleet's lock meet in the drain
        # source (admission → fleet), so calling into the controller
        # while holding fleet locks would invert the order
        admission_stats = self._admission.stats()
        with self._svc.lock:
            with self._lock:
                partitions = [{
                    "index": p.index,
                    "partition": p.key,
                    "size": p.size,
                    "klass": p.klass,
                    "status": p.status,
                    "worker": (self._svc.holder(p.index) or (None,))[0],
                    "epoch": self._partition_epoch.get(p.index),
                    "assignments": p.assignments,
                } for p in self._partitions]
                workers = {
                    w.wid: {
                        "drained": w.drained,
                        "dead": w.dead,
                        "drain_per_sec": round(
                            self._worker_beat.get(
                                w.wid, (0.0, 0.0, 0)
                            )[1], 3,
                        ),
                    } for w in self._svc.workers.values()
                }
                doc = {
                    "partitions": partitions,
                    "workers": workers,
                    "reassignments": [
                        dict(r) for r in self._svc.reassignments
                    ],
                    "fenced_rejections": [
                        dict(r) for r in self._svc.fenced
                    ],
                    "accounting": {
                        k: v for k, v in self._counters.items()
                        if k != "commit_faults"
                    },
                    "commit_faults": self._counters["commit_faults"],
                    "reject_causes": dict(self._reject_causes),
                    "rebalance": {
                        "count": len(self._rebalance_lat),
                        "max_latency_s": round(
                            max(self._rebalance_lat, default=0.0), 3
                        ),
                    },
                    "recruitment": {
                        **{k: int(v) for k, v in self._recruit.items()},
                        "max_workers": self._max_workers,
                    },
                    "degrade": {
                        "level": self._degrade.level
                        if self._degrade.enabled else 0,
                        "max_seen": self._degrade_max_seen,
                    },
                    "admission": admission_stats,
                    "wall_s": round(time.monotonic() - self._t0, 3),
                }
        return doc


# ------------------------------------------------------------ fleet worker
class _DataHandler(socketserver.StreamRequestHandler):
    """One front-door data connection: request lines in, result lines
    out (engine completion threads write under a per-connection lock)."""

    def handle(self):  # noqa: D102 — protocol loop
        worker = self.server.fleet_worker  # type: ignore[attr-defined]
        wlock = threading.Lock()
        while True:
            try:
                msg = recv_line(self.rfile)
            except (OSError, ValueError):
                break
            if msg is None:
                break
            try:
                worker.handle_serve(msg, self.connection, wlock)
            except Exception:
                break


class _DataServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FleetWorker:
    """One serve worker: wraps an engine (``ServeEngine`` or anything
    with its ``submit``/``close`` shape), joins the fleet, leases
    traffic partitions, heartbeats them with its measured drain rate,
    and serves routed requests over its data socket.

    A request is admitted only when the worker CURRENTLY holds the
    (partition, epoch) it was routed under — a mid-rebalance request is
    answered ``fenced`` so the front door resubmits to the real holder.
    Results are sent with the epoch they were admitted under; the front
    door's commit fence does the rest (a SIGSTOPped worker resuming
    past its TTL sends a stale-epoch result that can never commit)."""

    def __init__(self, coordinator: Tuple[str, int], worker_id: str,
                 engine, *, data_host: str = "127.0.0.1",
                 data_port: int = 0, own_engine: bool = True,
                 timeout: float = 30.0):
        self.worker_id = worker_id
        self.engine = engine
        self._own_engine = bool(own_engine)
        self.coordinator = (coordinator[0], int(coordinator[1]))
        self._lock = threading.RLock()
        self._held: Dict[int, int] = {}  # partition index -> epoch
        self._stop_event = threading.Event()
        self._drained = False
        self._coordinator_lost = False
        self._last_drain = (time.monotonic(), 0)
        #: live-autotune election tracking: the highest election epoch
        #: applied (beat replies re-deliver the current election every
        #: interval — each must apply at most once) and the callback
        #: that applies it locally (autotune_live.apply_winner over the
        #: engine's predictor, typically)
        self._live_epoch = 0
        self._on_live_tune: Optional[Any] = None
        self._data_server = _DataServer((data_host, int(data_port)),
                                        _DataHandler)
        self._data_server.fleet_worker = self  # type: ignore[attr-defined]
        self._sock = socket.create_connection(
            self.coordinator, timeout=connect_timeout(min(timeout, 5.0))
        )
        self._sock.settimeout(timeout)
        self._file = self._sock.makefile("rb")
        self._ctl_lock = threading.Lock()
        self.config = self._call({
            "op": "hello",
            "data_addr": list(self._data_server.server_address[:2]),
        })
        self._hb_interval = float(
            self.config.get("hb_interval_s") or 2.5
        )
        reg = getattr(engine, "metrics", None)
        self._obs: Optional[_fleetobs.WorkerObs] = (
            _fleetobs.WorkerObs(
                reg if hasattr(reg, "snapshot") else None
            )
            if _fleetobs.fleet_obs_enabled() else None
        )
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- control
    def _call(self, doc: dict) -> dict:
        doc = dict(doc)
        doc.setdefault("worker", self.worker_id)
        with self._ctl_lock:
            send_line(self._sock, doc)
            reply = recv_line(self._file)
        if reply is None:
            raise ConnectionError("fleet coordinator closed the "
                                  "connection")
        return reply

    def start(self) -> "FleetWorker":
        threads = [
            threading.Thread(target=self._data_server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             name=f"fleet-data-{self.worker_id}",
                             daemon=True),
            threading.Thread(target=self._lease_loop,
                             name=f"fleet-lease-{self.worker_id}",
                             daemon=True),
            threading.Thread(target=self._beat_loop,
                             name=f"fleet-beat-{self.worker_id}",
                             daemon=True),
        ]
        with self._lock:
            self._threads = threads
        for t in threads:
            t.start()
        return self

    def _lease_loop(self) -> None:
        """Keep leasing: a worker holds every partition the coordinator
        will grant it, and keeps polling so rebalanced/new partitions
        find a holder fast."""
        while not self._stop_event.is_set():
            try:
                grant = self._call({"op": "lease"})
            except (ConnectionError, OSError):
                # coordinator gone: flag it so a supervising loop
                # (the CLI) can exit instead of spinning forever
                if not self._stop_event.is_set():
                    with self._lock:
                        self._coordinator_lost = True
                return
            if grant.get("drained"):
                with self._lock:
                    self._drained = True
                return
            index = grant.get("index")
            if index is None:
                if self._stop_event.wait(
                    float(grant.get("wait_s", 0.2))
                ):
                    return
                continue
            with self._lock:
                self._held[int(index)] = int(grant["epoch"])

    def _beat_loop(self) -> None:
        while not self._stop_event.wait(self._hb_interval):
            try:
                self._beat_once()
            except (ConnectionError, OSError):
                pass  # missed beats ARE the liveness signal

    def _beat_once(self) -> dict:
        with self._lock:
            held = [[i, e] for i, e in self._held.items()]
        doc = {
            "op": "beat", "worker": self.worker_id, "held": held,
            "drain": self._drain_rate(), "pending": self._pending(),
        }
        w_obs = self._obs
        t_send = 0.0
        if w_obs is not None:
            # metrics delta + fresh spans + clock estimate ride the
            # liveness beat (bounded; old coordinators ignore the key)
            doc["obs"] = w_obs.attachment()
            t_send = time.perf_counter()
        reply = oneshot(self.coordinator, doc)
        if w_obs is not None:
            # reply stamped with the coordinator clock -> one midpoint
            # clock-offset sample per beat
            w_obs.clock_sample(t_send, reply.get("obs_ts"),
                               time.perf_counter())
        stale = reply.get("stale") or ()
        le = reply.get("live_tune")
        apply_cb = None
        with self._lock:
            for index, epoch in stale:
                if self._held.get(int(index)) == int(epoch):
                    del self._held[int(index)]
            if reply.get("drained"):
                self._drained = True
            # live-autotune election riding the beat reply: epoch-
            # guarded (the coordinator re-sends the current election on
            # every beat; each epoch applies at most once per worker)
            if isinstance(le, dict) and \
                    int(le.get("epoch") or 0) > self._live_epoch:
                self._live_epoch = int(le["epoch"])
                apply_cb = self._on_live_tune
        if apply_cb is not None:
            try:
                apply_cb(dict(le))
            except Exception:
                pass  # applying an election must never kill the beat
        return reply

    def on_live_tune(self, fn) -> None:
        """Register ``fn(election_doc)`` to apply coordinator elections
        delivered over beat replies (each epoch at most once) — wire it
        to ``autotune_live.apply_winner`` over this worker's
        predictor."""
        with self._lock:
            self._on_live_tune = fn

    def _drain_rate(self) -> float:
        """Requests/s from the engine's completed-counter delta between
        beats — the capacity evidence each beat carries."""
        counters = getattr(self.engine, "counters", None)
        completed = int((counters or {}).get("completed", 0)) \
            if isinstance(counters, dict) else 0
        now = time.monotonic()
        with self._lock:
            t_last, c_last = self._last_drain
            self._last_drain = (now, completed)
        dt = now - t_last
        if dt <= 0 or completed < c_last:
            return 0.0
        return (completed - c_last) / dt

    def _pending(self) -> int:
        stats = getattr(self.engine, "stats", None)
        if not callable(stats):
            return 0
        try:
            return int(stats().get("pending", 0))
        except Exception:
            return 0

    # ---------------------------------------------------------- data plane
    def holds(self, index: int, epoch: int) -> bool:
        with self._lock:
            return self._held.get(int(index)) == int(epoch)

    def handle_serve(self, msg: dict, conn, wlock) -> None:
        """One routed request: fence at receipt, submit to the engine,
        send the result line when the future resolves."""
        rid = str(msg.get("rid"))
        index = int(msg.get("partition", -1))
        epoch = int(msg.get("epoch", -1))
        base = {"op": "result", "rid": rid, "partition": index,
                "epoch": epoch, "worker": self.worker_id}
        ctx = _fleetobs.ctx_of(msg)
        t_recv = time.perf_counter() if ctx is not None else 0.0

        def reply(**fields):
            if ctx is not None:
                # the worker's hop of the propagated trace: receipt to
                # result-line, parented under the front door's root
                _fleetobs.add_remote_span(
                    "fleet.worker.serve", t_recv, time.perf_counter(),
                    ctx, rid=rid, worker=self.worker_id,
                    status=str(fields.get("status")),
                )
            doc = dict(base)
            doc.update(fields)
            try:
                with wlock:
                    send_line(conn, doc)
            except OSError:
                pass  # front door gone; it will resubmit on revoke

        if not self.holds(index, epoch):
            reply(status="fenced")
            return
        try:
            image = unpack_array(msg["image"])
            ex = unpack_array(msg["exemplars"])
            fut = self.engine.submit(
                image, ex, multi=bool(msg.get("multi")),
                k_real=msg.get("k_real"),
                priority=int(msg.get("priority") or 0),
                deadline_ms=msg.get("deadline_ms"),
            )
        except Exception as e:
            reply(status="error", message=f"{type(e).__name__}: {e}")
            return

        def on_done(f: Future, _reply=reply):
            try:
                exc = f.exception()
                if exc is None:
                    _reply(status="ok",
                           detections=pack_detections(f.result()))
                elif isinstance(exc, RejectedError):
                    _reply(status="rejected", cause=exc.cause,
                           message=str(exc))
                else:
                    _reply(status="error",
                           message=f"{type(exc).__name__}: {exc}")
            except Exception:
                pass  # the engine's completion thread must survive

        fut.add_done_callback(on_done)

    # ------------------------------------------------------------ lifecycle
    @property
    def held(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._held)

    @property
    def drained(self) -> bool:
        with self._lock:
            return self._drained

    @property
    def coordinator_lost(self) -> bool:
        """True once the control connection died outside a stop() —
        the worker cannot lease again; supervising loops should exit
        (and let their process supervisor decide about a restart)."""
        with self._lock:
            return self._coordinator_lost

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_event.set()
        bye: Dict[str, Any] = {"op": "bye"}
        if self._obs is not None:
            # end-of-life flush: final metrics totals + remaining spans
            # (+ flight tail) ride the bye so a short-lived worker's
            # window still reconciles at the coordinator
            bye["obs"] = self._obs.attachment(final=True)
        try:
            self._call(bye)
        except (ConnectionError, OSError):
            pass
        try:  # shutdown-first: unblocks any reader before the close
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._data_server.shutdown()
        self._data_server.server_close()
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + max(timeout, 0.0)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        if self._own_engine:
            close = getattr(self.engine, "close", None)
            if callable(close):
                close()


# ------------------------------------------------------------ stub engine
class StubFleetPredictor:
    """Numpy-only Predictor stand-in for fleet drills (the
    test_overload stub pattern, exported so subprocess workers and the
    probe share ONE definition): instant host 'programs', no XLA. Each
    detection row 0 carries the request image's mean as its score — a
    deterministic per-image signature, so the probe can verify every
    routed result came from ITS image (crossed wires or double serves
    would show as signature mismatches). ``delay_s`` paces each
    program call (capacity control: kills land mid-batch, spikes
    saturate)."""

    def __init__(self, delay_s: float = 0.0, slots: int = 8):
        self.params = np.zeros((1,), np.float32)
        self.refiner_params = None
        self.delay_s = float(delay_s)
        self.slots = int(slots)

    def bucket_key(self, size, ex, multi=False, k_real=None):
        ex = np.asarray(ex, np.float32).reshape(-1, 4)
        k = int(k_real) if k_real is not None else len(ex)
        if multi:
            return ("multi", int(size), 9, k)
        return ("single", int(size), 9, len(ex))

    def _dets(self, images) -> dict:
        arr = np.asarray(images, np.float32)
        b = arr.shape[0]
        sig = arr.reshape(b, -1).mean(axis=1)
        dets = {
            "boxes": np.zeros((b, self.slots, 4), np.float32),
            "scores": np.zeros((b, self.slots), np.float32),
            "refs": np.zeros((b, self.slots, 2), np.float32),
            "valid": np.zeros((b, self.slots), bool),
        }
        dets["scores"][:, 0] = sig
        dets["valid"][:, 0] = True
        return dets

    def _run(self, images):
        if self.delay_s:
            time.sleep(self.delay_s)
        return self._dets(images)

    def _get_fn(self, capacity, donate=False):
        return lambda p, rp, image, ex, *a: self._run(image)

    def _get_multi_batched_fn(self, capacity, k, donate=False):
        return lambda p, rp, image, ex, k_real: self._run(image)

    def _get_backbone_fn(self):
        return lambda p, image: np.zeros(
            (np.asarray(image).shape[0], 2, 2, 4), np.float32
        )

    def _get_heads_fn(self, capacity, size):
        return lambda p, rp, feats, ex: self._run(
            np.zeros((np.asarray(feats).shape[0], 1, 1, 3), np.float32)
        )

    def __call__(self, image, exemplars):
        return self._run(np.asarray(image)[None]
                         if np.asarray(image).ndim == 3 else image)

    def predict_multi_exemplar(self, image, exemplars, k_real=None):
        return self._run(image)


def stub_signature(image) -> float:
    """The per-image signature StubFleetPredictor stamps into
    ``scores[0, 0]`` — float32 mean, computed exactly like the stub
    does so probe-side expectations match bitwise."""
    arr = np.asarray(image, np.float32)
    return float(arr.reshape(1, -1).mean(axis=1)[0])


def stub_engine(delay_s: float = 0.0, *, batch: int = 2,
                max_wait_ms: float = 5.0):
    """A real ServeEngine over the numpy stub predictor: the full
    batcher/staging/completion pipeline with zero XLA — what fleet
    drills and the elastic_serve_probe workers run."""
    from tmr_tpu.serve.engine import ServeEngine

    return ServeEngine(
        StubFleetPredictor(delay_s=delay_s), batch=batch,
        max_wait_ms=max_wait_ms, feature_cache=0, exemplar_cache=0,
    )
